"""Pipeline-parallel training engine.

Parity with reference ``deepspeed/runtime/pipe/engine.py`` (PipelineEngine,
``train_batch`` :296, instruction interpreter :1348-1377, p2p activation
exchange :828-1153): stages execute a 1F1B schedule, exchange activations
and activation-gradients, accumulate per-stage grads, and step together.

TPU re-design (SURVEY.md §7 hard part (a)):

* Each stage owns a **sub-mesh**: the slice of the global mesh at its ``pp``
  coordinate, with the remaining axes (dp/fsdp/tp/...) intact — ZeRO and TP
  compose per stage via the same ZeroShardingRules as the dense engine.
* The host walks the 1F1B clock stream (pipe/schedule.py) and dispatches
  per-stage **jitted programs**; JAX async dispatch overlaps stages on
  their devices. Activation transfer goes through pipe/transport.py
  (``tpu.pipeline.transport``): a cross-mesh ``jax.device_put`` in a
  single process, or an in-program ``lax.ppermute`` over the joint
  ``(pp, dp, ...)`` mesh — the mode that makes multi-process pipeline
  parallelism work (replacing torch.distributed send/recv + meta
  exchange, reference pipe/p2p.py:48-161). Multi-process runs gate each
  stage's compute on ownership of its sub-mesh.
* Stage backward is **recompute-based** (jax.vjp inside one jitted program):
  only the stage *input* is stored per in-flight micro batch — the 1F1B
  activation footprint without hook machinery.
* Tied layers (TiedLayerSpec) sync by summing grads across the owning stages
  after the clock stream (reference pipe/module.py:417-436 tied-comm
  allreduce).
"""

import contextlib
import os
import warnings
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from flax import serialization

from deepspeed_tpu.parallel.mesh import MeshTopology, set_default_topology
from deepspeed_tpu.runtime.checkpoint_engine import select_checkpoint_engine
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader
from deepspeed_tpu.runtime.lr_schedules import (
    LRScheduler,
    build_lr_scheduler,
    schedule_fn_from_config,
)
from deepspeed_tpu.runtime.optimizer import build_optimizer
from deepspeed_tpu.runtime.pipe.module import PipelineModule, TiedLayerSpec
from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule
from deepspeed_tpu.runtime.pipe.transport import (
    StageTransport,
    resolve_transport,
)
from deepspeed_tpu.runtime.zero.sharding import ZeroShardingRules
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import ThroughputTimer

# shared no-op phase context when the step profiler is off (zero syncs)
_NULL_PIPE_CTX = contextlib.nullcontext()


class _StageModule(nn.Module):
    """Sequentially composes the LayerSpecs of one stage. Layers keep their
    GLOBAL index in their param path so checkpoints are partition-invariant
    (reference names layers by global id in module state files)."""

    specs: Tuple
    global_offset: int

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True, pld_theta=None):
        import inspect

        for i, spec in enumerate(self.specs):
            layer = spec.typename(*spec.module_args,
                                  name=f"layer_{self.global_offset + i}",
                                  **spec.module_kwargs)
            sig = inspect.signature(spec.typename.__call__)
            kwargs = {}
            if "deterministic" in sig.parameters:
                kwargs["deterministic"] = deterministic
            # progressive layer drop rides through to the blocks that take
            # it (each knows its global depth via layer_idx)
            if pld_theta is not None and "pld_theta" in sig.parameters:
                kwargs["pld_theta"] = pld_theta
            x = layer(x, **kwargs)
        return x


class PipelineEngine:
    """Train a PipelineModule over the ``pp`` mesh axis."""

    def __init__(self, model: PipelineModule, config, topology=None,
                 optimizer=None, lr_scheduler=None, seed: int = 0):
        from deepspeed_tpu import comm
        from deepspeed_tpu.parallel.mesh import topology_from_config

        comm.init_distributed()
        self.module = model
        if not isinstance(config, DeepSpeedConfig):
            config = DeepSpeedConfig(config)
        self._config = config
        if topology is None:
            topology = topology_from_config(config.tpu.mesh_config)
        self.topology = topology
        set_default_topology(topology)

        self.num_stages = (model.num_stages or topology.size("pp"))
        assert self.num_stages == topology.size("pp"), (
            f"PipelineModule wants {self.num_stages} stages but mesh pp axis "
            f"is {topology.size('pp')}"
        )
        config._resolve_batch_triad(topology.data_parallel_size)

        self.gradient_accumulation_steps = config.gradient_accumulation_steps
        self.train_micro_batch_size_per_gpu = config.train_micro_batch_size_per_gpu
        self.train_batch_size = config.train_batch_size
        self.micro_batches = self.gradient_accumulation_steps
        self.gradient_clipping = config.gradient_clipping
        self.zero_stage = config.zero_config.stage
        assert self.zero_stage <= 1, (
            "ZeRO-2/3 cannot pair with pipeline parallelism (reference "
            "engine raises the same; grads must persist across the schedule)"
        )

        # ---- stage sub-meshes -------------------------------------------
        # mesh devices have shape (pp, dp, fsdp, ep, sp, tp)
        sizes = topology.axis_sizes
        self.stage_topos: List[MeshTopology] = []
        for s in range(self.num_stages):
            devs = topology.mesh.devices[s].flatten()
            self.stage_topos.append(MeshTopology(
                pp=1, dp=sizes["dp"], fsdp=sizes["fsdp"], ep=sizes["ep"],
                sp=sizes["sp"], tp=sizes["tp"], devices=list(devs),
            ))

        # ---- partition layers into stages --------------------------------
        bounds = model.partition(self.num_stages)
        self.stage_bounds = bounds
        self.stage_modules: List[_StageModule] = []
        for s in range(self.num_stages):
            specs = tuple(model.layer_specs[bounds[s]:bounds[s + 1]])
            self.stage_modules.append(
                _StageModule(specs=specs, global_offset=bounds[s]))

        # tied-layer registry: key -> [(stage, local param name)]
        self.tied_groups: Dict[str, List[Tuple[int, str]]] = {}
        for s in range(self.num_stages):
            for i, spec in enumerate(model.layer_specs[bounds[s]:bounds[s + 1]]):
                if isinstance(spec, TiedLayerSpec):
                    self.tied_groups.setdefault(spec.key, []).append(
                        (s, f"layer_{bounds[s] + i}"))

        # ---- stage-to-stage transport ------------------------------------
        # tpu.pipeline.transport: auto|ppermute|device_put (see
        # pipe/transport.py for the trade-off)
        self.transport_mode = resolve_transport(
            config.tpu.pipeline_config.transport)
        self.transport = StageTransport(
            topology, self.stage_topos, self.transport_mode)
        self._multiprocess = jax.process_count() > 1
        if self._multiprocess and self.transport_mode == "device_put":
            logger.warning(
                "pipeline transport=device_put on a multi-process mesh: "
                "cross-host device_put needs the backend's transfer server "
                "and hangs on backends without one — prefer "
                "tpu.pipeline.transport: ppermute")
        if self._multiprocess and self.tied_groups:
            raise NotImplementedError(
                "tied pipeline layers across processes are not supported "
                "yet: tied-weight sync is host-driven (device_get/put) and "
                "cannot reach non-addressable stages")

        # ---- optimizer / schedule ----------------------------------------
        self.lr_scheduler, self._schedule_fn = self._configure_lr(lr_scheduler)
        if optimizer is not None and isinstance(
                optimizer, optax.GradientTransformation):
            self._tx = optimizer
        else:
            self._tx = build_optimizer(
                config.optimizer.type, config.optimizer.params,
                self._schedule_fn, use_pallas=config.tpu.use_pallas_optimizer)
        self.optimizer_adapter = self._tx  # returned from initialize()

        # curriculum learning + progressive layer drop compose with the
        # pipeline exactly as with the dense engine (reference
        # engine.py:1629-1663 sets both up engine-agnostically): curriculum
        # truncates the micro batches before they enter the schedule; PLD
        # threads a per-step theta into every stage's fwd/bwd programs
        self.curriculum_scheduler = None
        if config.curriculum_learning.enabled:
            from deepspeed_tpu.runtime.data_pipeline import (
                CurriculumScheduler)

            self.curriculum_scheduler = CurriculumScheduler(
                config.curriculum_learning)
        self.progressive_layer_drop = None
        if config.progressive_layer_drop.enabled:
            from deepspeed_tpu.runtime.progressive_layer_drop import (
                ProgressiveLayerDrop)

            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=config.progressive_layer_drop.theta,
                gamma=config.progressive_layer_drop.gamma)

        self.checkpoint_engine = select_checkpoint_engine(config)
        self._rng = jax.random.PRNGKey(seed)
        self._initialized = False
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size,
            steps_per_output=config.steps_per_print)

        # step-level performance tracer (docs/observability.md); pipeline
        # phases: dataloader, h2d, schedule (the 1F1B clock stream) and
        # optimizer. None when disabled — zero added syncs.
        self.step_profiler = None
        if config.step_profiler.enabled:
            from deepspeed_tpu.profiling.step_profiler import StepProfiler

            self.step_profiler = StepProfiler(config.step_profiler)
        # per-stage compiled programs noted (as avals) during the first
        # profiled step so compiled_memory_analysis can re-lower them as
        # compile-cache hits AFTER the envelope closes (Mem/* export)
        self._mem_programs: Dict[str, Tuple[Any, tuple]] = {}

        # cluster health plane (docs/recovery.md "Cluster health & SDC
        # defense"): out-of-band liveness + straggler beats — exactly the
        # engine where they matter most, since a stalled peer parks every
        # other process inside a ppermute until the plane (not N local
        # watchdogs) pulls the plug. The pipe engine feeds steps only,
        # not param digests: each stage's params replicate over that
        # stage's own sub-mesh, so digests are not comparable between
        # stage-owning processes.
        self.health_plane = None
        ch_cfg = config.tpu.cluster_health_config
        if ch_cfg.resolve_enabled(jax.process_count()):
            from deepspeed_tpu.runtime.health import ClusterHealthPlane

            self.health_plane = ClusterHealthPlane(
                jax.process_index(), jax.process_count(), ch_cfg)
            self.health_plane.start()

        log_dist(
            f"PipelineEngine: stages={self.num_stages}, "
            f"bounds={bounds}, micro_batches={self.micro_batches}, "
            f"mesh={topology}", ranks=[0],
        )

    # ------------------------------------------------------------------
    def _configure_lr(self, lr_scheduler):
        cfg = self._config
        if lr_scheduler is None and cfg.scheduler.type is not None:
            return (build_lr_scheduler(cfg.scheduler.type, cfg.scheduler.params),
                    schedule_fn_from_config(cfg.scheduler.type,
                                            cfg.scheduler.params))
        if isinstance(lr_scheduler, LRScheduler):
            return lr_scheduler, lr_scheduler.schedule_fn
        if callable(lr_scheduler):
            return LRScheduler(lr_scheduler), lr_scheduler
        return None, None

    # ------------------------------------------------------------------
    # lazy init: build per-stage params/opt-state on their sub-meshes
    # ------------------------------------------------------------------
    def _init_state(self, first_input_avals):
        """Materialize per-stage params/opt state from the FIRST input's
        avals. The whole chain is aval-driven: every process walks it
        host-side (eval_shape), and each stage's state is materialized
        only by its owners (a jit over a fully non-addressable sub-mesh
        is illegal in multi-controller JAX). Flax init depends only on
        rng + shapes, so seeding the chain with zeros keeps parameters
        identical across transports and process layouts."""
        self._params: List[Any] = []
        self._opt_states: List[Any] = []
        self._param_shardings: List[Any] = []
        self._opt_shardings: List[Any] = []
        self._acc_grads: List[Any] = []
        self._rules: List[ZeroShardingRules] = []
        self._fwd_fns: List[Any] = [None] * self.num_stages
        self._bwd_fns: List[Any] = [None] * self.num_stages
        self._apply_fns: List[Any] = [None] * self.num_stages
        self._apply_fns_nodonate: List[Any] = [None] * self.num_stages
        # per-stage input/output avals: the transport needs them on EVERY
        # process (receivers assemble buffers before any data arrives)
        self._stage_in_avals: List[Any] = []
        self._stage_out_avals: List[Any] = []
        # param SHAPES (host-side avals) are kept on every process: they
        # let eval_batch re-derive activation avals for arbitrary batch
        # shapes without owning the stage's params
        self._stage_param_shapes: List[Any] = []

        x_aval = jax.tree.map(
            lambda v: jax.ShapeDtypeStruct(
                tuple(v.shape), jnp.asarray(v).dtype
                if not hasattr(v, "dtype") else v.dtype),
            first_input_avals)
        rng = self._rng
        for s in range(self.num_stages):
            topo = self.stage_topos[s]
            mod = self.stage_modules[s]
            rules = ZeroShardingRules(topo, stage=self.zero_stage,
                                      tp_rules=self.module.tp_rules)
            self._rules.append(rules)
            self._stage_in_avals.append(x_aval)
            rng_s = jax.random.fold_in(rng, s)

            def init_fn(r, xv):
                return mod.init({"params": r}, xv, deterministic=True)["params"]

            shapes = jax.eval_shape(init_fn, rng_s, x_aval)
            self._stage_param_shapes.append(shapes)
            if self.transport.owns_stage(s):
                p_shard = rules.param_sharding_tree(shapes)
                xz = self._zeros_on_stage(x_aval, s)
                params = jax.jit(init_fn, out_shardings=p_shard)(rng_s, xz)
                opt_shapes = jax.eval_shape(self._tx.init, shapes)
                o_shard = rules.opt_sharding_tree(opt_shapes, shapes)
                opt_state = jax.jit(
                    self._tx.init, out_shardings=o_shard)(params)
                acc = jax.tree.map(
                    lambda v: jnp.zeros(v.shape, jnp.float32), params)
            else:
                params = opt_state = acc = p_shard = o_shard = None
            self._params.append(params)
            self._opt_states.append(opt_state)
            self._param_shardings.append(p_shard)
            self._opt_shardings.append(o_shard)
            self._acc_grads.append(acc)
            # trace shapes through this stage for the next one's init
            x_aval = jax.eval_shape(
                lambda p, xv, m=mod: m.apply({"params": p}, xv,
                                             deterministic=True),
                shapes, x_aval)
            self._stage_out_avals.append(x_aval)
        self._sync_tied_params()
        self._initialized = True
        n = sum(int(np.prod(v.shape)) for p in self._params
                if p is not None for v in jax.tree.leaves(p))
        log_dist(f"pipeline state materialized: {n/1e6:.1f}M params over "
                 f"{self.num_stages} stages "
                 f"(transport={self.transport_mode})", ranks=[0])

    def _zeros_on_stage(self, aval_tree, s):
        """Zeros with the stage's batch sharding, built in-program (no
        host buffer; dispatched only by the stage's owners)."""
        sharding = self.stage_topos[s].batch_sharding()
        return jax.jit(
            lambda: jax.tree.map(
                lambda a: jnp.zeros(a.shape, a.dtype), aval_tree),
            out_shardings=sharding)()

    # ------------------------------------------------------------------
    # per-stage compiled programs
    # ------------------------------------------------------------------
    def _use_pld(self) -> bool:
        return self.progressive_layer_drop is not None

    def _pld_theta_now(self):
        """Host-side theta for this step (the interpreter is host-driven, so
        unlike the dense engine's in-graph form the schedule is evaluated
        here and passed as a traced scalar — no recompile per step)."""
        self.progressive_layer_drop.update_state(self.global_steps)
        return jnp.float32(self.progressive_layer_drop.get_theta())

    def _fwd_fn(self, s):
        if self._fwd_fns[s] is None:
            mod = self.stage_modules[s]

            if self._use_pld():
                def f(params, x, rng, theta):
                    return mod.apply({"params": params}, x,
                                     deterministic=False,
                                     rngs={"dropout": rng},
                                     pld_theta=theta)
            else:
                def f(params, x, rng):
                    return mod.apply({"params": params}, x,
                                     deterministic=False,
                                     rngs={"dropout": rng})

            self._fwd_fns[s] = jax.jit(f)
        return self._fwd_fns[s]

    def _loss_fn(self, s, params, x, labels, rng, theta=None):
        mod = self.stage_modules[s]
        kw = {"pld_theta": theta} if theta is not None else {}
        out = mod.apply({"params": params}, x, deterministic=False,
                        rngs={"dropout": rng}, **kw)
        if self.module.loss_fn is not None:
            return self.module.loss_fn(out, labels)
        return out  # last layer already returns loss

    def _bwd_fn(self, s):
        """Jitted recompute-backward: (params, x, g_out|labels) ->
        (g_params, g_x[, loss])."""
        if self._bwd_fns[s] is None:
            mod = self.stage_modules[s]
            last = s == self.num_stages - 1
            gas = self.micro_batches
            use_pld = self._use_pld()

            if last:
                def b(params, x, labels, rng, theta=None):
                    def lf(p, xv):
                        return self._loss_fn(s, p, xv, labels, rng,
                                             theta) / gas

                    (loss), vjp = jax.vjp(lf, params, x)
                    gp, gx = vjp(jnp.float32(1.0))
                    return gp, gx, loss * gas
            else:
                def b(params, x, g, rng, theta=None):
                    def f(p, xv):
                        kw = {"pld_theta": theta} if theta is not None \
                            else {}
                        return mod.apply({"params": p}, xv,
                                         deterministic=False,
                                         rngs={"dropout": rng}, **kw)

                    _, vjp = jax.vjp(f, params, x)
                    gp, gx = vjp(g)
                    return gp, gx
            # pld off: jit the 4-arg form so call sites stay uniform
            self._bwd_fns[s] = jax.jit(b) if use_pld else jax.jit(
                lambda params, x, gl, rng: b(params, x, gl, rng))
        return self._bwd_fns[s]

    def _apply_fn(self, s, donate=True):
        fns = self._apply_fns if donate else self._apply_fns_nodonate
        if fns[s] is None:
            tx = self._tx

            def apply_step(params, opt_state, acc, factor):
                grads = jax.tree.map(lambda g: g * factor, acc)
                updates, new_opt = tx.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                zero = jax.tree.map(jnp.zeros_like, acc)
                return new_params, new_opt, zero

            kw = {"donate_argnums": (0, 1, 2)} if donate else {}
            fns[s] = jax.jit(
                apply_step,
                out_shardings=(self._param_shardings[s],
                               self._opt_shardings[s], None), **kw)
        return fns[s]

    # ------------------------------------------------------------------
    # data plumbing
    # ------------------------------------------------------------------
    def _apply_curriculum(self, batch: Dict[str, Any]):
        """Truncate sequence tensors to the scheduled difficulty before
        they enter the 1F1B schedule (same transform as the dense
        engine's _apply_curriculum — shared helper so they cannot drift)."""
        from deepspeed_tpu.runtime.data_pipeline import (
            truncate_batch_to_difficulty)

        seqlen = self.curriculum_scheduler.update_difficulty(
            self.global_steps + 1)
        return truncate_batch_to_difficulty(batch, seqlen)

    def _split_batch(self, batch: Dict[str, Any]):
        """First-stage inputs vs last-stage labels (reference loads micro
        batches at the first and last stages, pipe/engine.py:787)."""
        batch = dict(batch)
        labels = batch.pop("labels", None)
        inputs = batch["input_ids"] if set(batch) == {"input_ids"} else batch
        return inputs, labels

    def _put(self, tree, stage):
        """Host batch -> the stage's sub-mesh. Multi-process: every
        process sees the same GLOBAL batch (transport data contract) and
        owners assemble their addressable shards of it; non-owners get
        None (they never touch the stage's compute)."""
        sharding = self.stage_topos[stage].batch_sharding()
        if not self._multiprocess:
            return jax.tree.map(
                lambda v: jax.device_put(jnp.asarray(v), sharding), tree)
        if not self.transport.owns_stage(stage):
            return None

        def put_leaf(v):
            v = np.asarray(v)
            shards = [
                jax.device_put(v[idx], dev) for dev, idx in
                sharding.addressable_devices_indices_map(v.shape).items()
            ]
            return jax.make_array_from_single_device_arrays(
                v.shape, sharding, shards)

        return jax.tree.map(put_leaf, tree)

    def deepspeed_io(self, dataset, collate_fn=None, shuffle=True):
        global_micro = (self.train_micro_batch_size_per_gpu
                        * self.topology.data_parallel_size)
        return DeepSpeedDataLoader(dataset, batch_size=global_micro,
                                   shuffle=shuffle, drop_last=True,
                                   collate_fn=collate_fn)

    # ------------------------------------------------------------------
    # the 1F1B interpreter (reference _exec_schedule, pipe/engine.py:1361)
    # ------------------------------------------------------------------
    def train_batch(self, data_iter):
        # stage fns trace lazily and model modules (VocabEmbed) read the
        # ambient topology at trace time — re-assert this engine's mesh
        set_default_topology(self.topology)
        prof = self.step_profiler
        if prof is not None:
            prof.begin_step(self.global_steps)

        def _phase(name):
            return prof.phase(name) if prof is not None else _NULL_PIPE_CTX

        M, S = self.micro_batches, self.num_stages
        inputs, labels = [], []
        for _ in range(M):
            with _phase("dataloader"):
                batch = next(data_iter)
            if self.curriculum_scheduler is not None:
                batch = self._apply_curriculum(batch)
            x, lab = self._split_batch(batch)
            if not self._initialized:
                self._init_state(jax.tree.map(
                    lambda v: jax.ShapeDtypeStruct(
                        np.asarray(v).shape, np.asarray(v).dtype), x))
            with _phase("h2d"):
                inputs.append(self._put(x, 0))
                labels.append(self._put(lab, S - 1)
                              if lab is not None else None)

        self._rng, step_rng = jax.random.split(self._rng)
        rngs = [[jax.random.fold_in(jax.random.fold_in(step_rng, s), m)
                 for m in range(M)] for s in range(S)]
        theta = self._pld_theta_now() if self._use_pld() else None
        self.tput_timer.start()

        acts: Dict[Tuple[int, int], Any] = {}    # (stage, mb) -> stage input
        grads_in: Dict[int, Any] = {}            # mb -> g wrt next-stage input
        losses = []

        owns = self.transport.owns_stage
        sched = TrainSchedule(M, S)
        with _phase("compiled_step"):
            for clock in sched.clocks():
                for ins in clock:
                    s, m = ins.stage, ins.micro_batch
                    if ins.op == "load":
                        if owns(0):
                            acts[(0, m)] = inputs[m]
                    elif ins.op == "forward":
                        # last stage fwd is fused into its backward
                        # (recompute); transfers run on EVERY process —
                        # ppermute is a joint-mesh collective
                        if s < S - 1:
                            out = None
                            if owns(s):
                                x = acts[(s, m)]
                                fargs = (self._params[s], x, rngs[s][m]) + (
                                    (theta,) if theta is not None else ())
                                self._note_mem_call(f"fwd_stage{s}",
                                                    self._fwd_fn(s), fargs)
                                out = self._fwd_fn(s)(*fargs)
                            nxt = self.transport.send_forward(
                                out, s, self._stage_out_avals[s])
                            if owns(s + 1):
                                acts[(s + 1, m)] = nxt
                    elif ins.op == "backward":
                        gx = None
                        if owns(s):
                            x = acts.pop((s, m))
                            textra = (theta,) if theta is not None else ()
                            if s == S - 1:
                                bargs = (self._params[s], x, labels[m],
                                         rngs[s][m]) + textra
                                self._note_mem_call(f"bwd_stage{s}",
                                                    self._bwd_fn(s), bargs)
                                gp, gx, loss = self._bwd_fn(s)(*bargs)
                                losses.append(loss)
                            else:
                                g = grads_in.pop(m)
                                bargs = (self._params[s], x, g,
                                         rngs[s][m]) + textra
                                self._note_mem_call(f"bwd_stage{s}",
                                                    self._bwd_fn(s), bargs)
                                gp, gx = self._bwd_fn(s)(*bargs)
                            self._acc_grads[s] = jax.tree.map(
                                jnp.add, self._acc_grads[s], gp)
                        if s > 0:
                            gprev = self.transport.send_backward(
                                gx, s, self._stage_in_avals[s])
                            if owns(s - 1):
                                grads_in[m] = gprev

            self._sync_tied_grads()
        with _phase("optimizer"):
            self._optimizer_step()
        self.global_steps += 1
        self.micro_steps += M
        self.global_samples += self.train_batch_size
        if self.health_plane is not None:
            self.health_plane.notify_step(self.global_steps)
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self.tput_timer.stop(global_step=True)
        if prof is not None:
            prof.end_step(self.global_steps)
            if self._mem_programs and not prof.has_memory():
                self._capture_compiled_memory()
        if self._multiprocess:
            # broadcast the last stage's per-microbatch losses to every
            # process through the [S]-slot psum (collective: all call it)
            contribs = {}
            if owns(S - 1):
                contribs[S - 1] = np.stack(
                    [np.asarray(l, np.float32) for l in losses])
            loss_vec = self.transport.psum_stage_scalars(
                contribs, shape=(M,))
            mean_loss = jnp.asarray(loss_vec.mean(), jnp.float32)
        else:
            mean_loss = jnp.mean(
                jnp.stack([jnp.asarray(l) for l in losses]))
        if self.global_steps % self._config.steps_per_print == 0:
            log_dist(f"pipe step={self.global_steps} loss={float(mean_loss):.4f}",
                     ranks=[0])
        return mean_loss

    def _note_mem_call(self, key: str, fn, args) -> None:
        """Remember (fn, avals-of-args) for a compiled stage program so
        its ``memory_analysis()`` can be read after the step. Avals only
        — holding the concrete arrays would pin a whole step's buffers.
        Active solely until the profiler has its memory breakdown."""
        prof = self.step_profiler
        if (prof is None or prof.has_memory()
                or key in self._mem_programs):
            return
        avals = tuple(
            jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
                if hasattr(x, "shape") else x, a)
            for a in args)
        self._mem_programs[key] = (fn, avals)

    def _capture_compiled_memory(self) -> None:
        """Per-stage XLA memory breakdown -> profiler ``Mem/*`` export.
        Each lowering is a compile-cache hit (same fn, same avals as the
        step that just ran); runs after the fenced envelope closed, so it
        is never charged to a measured span."""
        from deepspeed_tpu.telemetry.memory import (
            compiled_memory_analysis,
            summarize_program_memory,
        )

        programs, self._mem_programs = self._mem_programs, {}
        try:
            mems = {key: compiled_memory_analysis(fn, *avals)
                    for key, (fn, avals) in programs.items()}
            if mems:
                self.step_profiler.set_memory(
                    summarize_program_memory(mems))
        except Exception as e:  # pragma: no cover - backend w/o the API
            logger.warning(f"pipe compiled_step memory unavailable: {e}")

    def eval_batch(self, batch):
        """Wavefront forward (reference InferenceSchedule); returns last-stage
        output (loss if labels present)."""
        set_default_topology(self.topology)
        x, labels = self._split_batch(batch)
        if not self._initialized:
            self._init_state(jax.tree.map(
                lambda v: jax.ShapeDtypeStruct(
                    np.asarray(v).shape, np.asarray(v).dtype), x))
        owns = self.transport.owns_stage
        # eval batches need not match the training batch shape: re-derive
        # activation avals for THIS batch (host-side, every process)
        aval = jax.tree.map(
            lambda v: jax.ShapeDtypeStruct(
                np.asarray(v).shape, np.asarray(v).dtype), x)
        out_avals = []
        for s in range(self.num_stages):
            aval = jax.eval_shape(
                lambda p, xv, m=self.stage_modules[s]: m.apply(
                    {"params": p}, xv, deterministic=True),
                self._stage_param_shapes[s], aval)
            out_avals.append(aval)
        x = self._put(x, 0)
        for s in range(self.num_stages - 1):
            out = None
            if owns(s):
                out = self.stage_modules[s].apply(
                    {"params": self._params[s]}, x, deterministic=True)
            x = self.transport.send_forward(out, s, out_avals[s])
        s = self.num_stages - 1
        out = None
        if owns(s):
            out = self.stage_modules[s].apply(
                {"params": self._params[s]}, x, deterministic=True)
            if labels is not None and self.module.loss_fn is not None:
                out = self.module.loss_fn(out, self._put(labels, s))
        if self._multiprocess and labels is not None \
                and self.module.loss_fn is not None:
            # scalar loss: broadcast so every process returns the same
            val = self.transport.psum_stage_scalars(
                {s: out} if owns(s) else {})
            return jnp.asarray(val, jnp.float32)
        return out

    # ------------------------------------------------------------------
    def _sync_tied_params(self):
        """Copy the first owner's tied-layer params to every other owner so
        tied weights start identical; with grads synced every step they stay
        identical (reference broadcasts tied weights from the owner rank at
        init, pipe/module.py tied-weight setup)."""
        for key, members in self.tied_groups.items():
            if len(members) < 2:
                continue
            s0, name0 = members[0]
            src = jax.device_get(self._params[s0][name0])
            for s, lname in members[1:]:
                tied = jax.tree.map(jnp.asarray, src)
                self._params[s] = dict(self._params[s])
                self._params[s][lname] = jax.device_put(
                    tied, self.stage_topos[s].replicated())

    def _sync_tied_grads(self):
        """Sum grads of tied layers across their stages and distribute back
        (reference pipe/module.py:417-436 allreduce over the tied comm
        group)."""
        for key, members in self.tied_groups.items():
            if len(members) < 2:
                continue
            total = None
            for s, lname in members:
                g = self._acc_grads[s][lname]
                g = jax.device_put(
                    g, self.stage_topos[members[0][0]].replicated())
                total = g if total is None else jax.tree.map(jnp.add, total, g)
            for s, lname in members:
                self._acc_grads[s] = dict(self._acc_grads[s])
                self._acc_grads[s][lname] = jax.device_put(
                    total, self.stage_topos[s].replicated())

    def _optimizer_step(self):
        # global grad-norm clip across stages (reference engine clips with
        # the norm over ALL pipeline ranks); loss already carries the 1/gas
        # scale, so no extra factor here
        factor = 1.0
        if self.gradient_clipping and self.gradient_clipping > 0:
            if self._multiprocess:
                # cross-stage norm needs every stage's contribution; the
                # [S]-slot psum is the collective every process joins
                contribs = {
                    s: float(optax.global_norm(self._acc_grads[s]) ** 2)
                    for s in range(self.num_stages)
                    if self.transport.owns_stage(s)
                }
                sq = float(self.transport.psum_stage_scalars(contribs))
            else:
                sq = 0.0
                for s in range(self.num_stages):
                    sq += float(optax.global_norm(self._acc_grads[s]) ** 2)
            gnorm = float(np.sqrt(sq))
            clip = min(1.0, self.gradient_clipping / (gnorm + 1e-6))
        else:
            clip = 1.0
        for s in range(self.num_stages):
            if not self.transport.owns_stage(s):
                continue
            aargs = (self._params[s], self._opt_states[s],
                     self._acc_grads[s], jnp.float32(clip * factor))
            self._note_mem_call(f"apply_stage{s}", self._apply_fn(s), aargs)
            try:
                out = self._apply_fn(s)(*aargs)
            except Exception as e:  # XLA donation-alias rejection
                # When a stage's params arrive in a different sharding than
                # the apply program's out_shardings (first step after a
                # replicated init/restore), XLA cannot alias the donated
                # input with the resharded output and aborts the launch
                # with an INTERNAL aliasing error. The buffers are intact
                # at that point, so rerun through an alias-free program —
                # donation is only a memory optimization.
                if "aliased" not in str(e):
                    raise
                warnings.warn(
                    f"stage {s} optimizer apply could not donate its "
                    f"buffers ({e}); retrying without donation",
                    RuntimeWarning)
                out = self._apply_fn(s, donate=False)(*aargs)
            self._params[s], self._opt_states[s], self._acc_grads[s] = out

    # ------------------------------------------------------------------
    # checkpoint (per-stage files; reference saves per-pp-rank states)
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True):
        assert self._initialized
        tag = tag or f"global_step{self.global_steps}"
        import glob as _glob
        import pickle

        pre_existing = set(_glob.glob(os.path.join(
            save_dir, str(tag), "layer_bounds_*_model_states.msgpack")))
        pre_existing |= set(_glob.glob(os.path.join(
            save_dir, str(tag), "layer_bounds_*_optim_states.msgpack")))
        written = set()
        for s in range(self.num_stages):
            # multi-process: each stage's files are written once, by the
            # lowest-indexed owning process (layout is transport- and
            # process-count-invariant: global layer names, same bounds)
            if not self._stage_first_owner(s):
                continue
            stem = (f"layer_bounds_{self.stage_bounds[s]}_"
                    f"{self.stage_bounds[s+1]}")
            path = os.path.join(save_dir, str(tag),
                                f"{stem}_model_states.msgpack")
            self.checkpoint_engine.save(
                {"module": serialization.to_state_dict(self._params[s])},
                path)
            written.add(path)
            # per-stage optimizer state (reference saves per-pp-rank optim
            # states the same way, pipe/engine.py module_state_dict side)
            opath = os.path.join(save_dir, str(tag),
                                 f"{stem}_optim_states.msgpack")
            self.checkpoint_engine.save(
                {"optimizer": serialization.to_state_dict(
                    self._opt_states[s])}, opath)
            written.add(opath)
        # engine counters + lr schedule: without these a resumed run
        # silently restarts every step-indexed schedule (curriculum
        # difficulty, PLD theta, lr warmup) from zero. Saved through the
        # checkpoint engine (pickled bytes in a msgpack envelope) so the
        # meta shares the commit durability barrier with the stage files.
        if jax.process_index() == 0:
            meta = {
                "global_steps": self.global_steps,
                "global_samples": self.global_samples,
                "micro_steps": self.micro_steps,
                "lr_scheduler": (self.lr_scheduler.state_dict()
                                 if self.lr_scheduler else {}),
                "client_state": client_state or {},
            }
            self.checkpoint_engine.save(
                {"meta": np.frombuffer(pickle.dumps(meta), np.uint8)},
                os.path.join(save_dir, str(tag),
                             "pipe_engine_states.msgpack"))
        # durability barrier BEFORE advertising 'latest' (async engine:
        # save() only enqueues; files land at commit)
        self.checkpoint_engine.commit(tag)
        # only now purge stale files from an earlier save at a DIFFERENT
        # pipeline degree (their bounds-keyed names differ, and a merging
        # load could pick them up): a crash any earlier leaves the
        # previous complete set on disk
        if self._multiprocess:
            # each process only knows the files ITS stages wrote; purging
            # by local difference would delete peers' fresh files
            pre_existing = written = set()
        for stale in sorted(pre_existing - written):
            try:
                os.remove(stale)
            except FileNotFoundError:
                pass  # concurrently removed — already the desired state
            except OSError as e:
                # must not fail an otherwise-durable save, but a SURVIVING
                # stale bounds file is not cosmetic: a later degree-changed
                # load merges every bounds file it globs, stale included —
                # say so loudly
                from deepspeed_tpu.utils.logging import logger

                logger.warning(
                    "could not purge stale pipeline checkpoint file %s "
                    "(%s); a later load at a different pipeline degree "
                    "may merge its outdated layers — remove it manually",
                    stale, e)
        if save_latest and jax.process_index() == 0:
            from deepspeed_tpu.runtime import checkpoint_manifest

            checkpoint_manifest.write_latest(save_dir, tag)
        return True

    def _stage_first_owner(self, s: int) -> bool:
        """True when this process is the lowest-indexed owner of stage
        ``s`` (single process: always True for every stage)."""
        if not self.transport.owns_stage(s):
            return False
        first = min(d.process_index
                    for d in self.stage_topos[s].mesh.devices.flat)
        return first == jax.process_index()

    def load_checkpoint(self, load_dir, tag=None,
                        load_optimizer_states=True, **_):
        """Reload stage params; the checkpoint's pipeline degree need not
        match this engine's. Layers are stored under GLOBAL names
        (``layer_N``) in per-stage files keyed by their layer bounds, so a
        degree change just merges every file and re-splits by the current
        bounds (reference ``checkpoint/reshape_3d_utils.py`` reshapes the
        same way, offline; here the load does it in place). Optimizer
        state and engine counters restore at the SAME degree; a
        degree-changed load restores params + counters and restarts the
        optimizer state fresh (the reference reshapes optimizer states
        offline through its universal-checkpoint tooling)."""
        import glob as _glob
        import pickle

        if tag is None:
            with open(os.path.join(load_dir, "latest")) as f:
                tag = f.read().strip()
        assert self._initialized, "run one batch (or init) before load"
        exact = [os.path.join(
            load_dir, str(tag),
            f"layer_bounds_{self.stage_bounds[s]}_"
            f"{self.stage_bounds[s + 1]}_model_states.msgpack")
            for s in range(self.num_stages)]
        same_degree = all(os.path.exists(f) for f in exact)
        if same_degree:
            files = exact        # same degree: read only our own files
        else:
            files = sorted(_glob.glob(os.path.join(
                load_dir, str(tag), "layer_bounds_*_model_states.msgpack")))
        if not files:
            raise FileNotFoundError(
                f"no layer_bounds_*_model_states.msgpack under "
                f"{load_dir}/{tag}")
        merged = {}
        for f in files:
            merged.update(self.checkpoint_engine.load(f)["module"])
        for s in range(self.num_stages):
            if not self.transport.owns_stage(s):
                continue
            want = set(self._params[s])
            missing = want - set(merged)
            if missing:
                raise KeyError(
                    f"checkpoint {tag} lacks layers {sorted(missing)} for "
                    f"stage {s} (saved layers: {sorted(merged)})")
            restored = serialization.from_state_dict(
                self._params[s], {k: merged[k] for k in self._params[s]})
            self._params[s] = jax.jit(
                lambda t: t, out_shardings=self._param_shardings[s])(restored)
        self._sync_tied_params()

        client_state = {}
        meta_path = os.path.join(load_dir, str(tag),
                                 "pipe_engine_states.msgpack")
        if os.path.exists(meta_path):
            meta = pickle.loads(np.asarray(
                self.checkpoint_engine.load(meta_path)["meta"]).tobytes())
            self.global_steps = int(meta["global_steps"])
            self.global_samples = int(meta["global_samples"])
            self.micro_steps = int(meta["micro_steps"])
            if self.lr_scheduler is not None and meta.get("lr_scheduler"):
                self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
            client_state = meta.get("client_state", {})
        else:
            log_dist(f"checkpoint {tag} predates engine-state files; "
                     "step counters not restored", ranks=[0])

        if load_optimizer_states:
            if same_degree:
                restored_any = False
                for s in range(self.num_stages):
                    if not self.transport.owns_stage(s):
                        continue
                    opath = os.path.join(
                        load_dir, str(tag),
                        f"layer_bounds_{self.stage_bounds[s]}_"
                        f"{self.stage_bounds[s + 1]}_optim_states.msgpack")
                    if not os.path.exists(opath):
                        continue
                    ostate = self.checkpoint_engine.load(opath)["optimizer"]
                    restored = serialization.from_state_dict(
                        self._opt_states[s], ostate)
                    self._opt_states[s] = jax.jit(
                        lambda t: t,
                        out_shardings=self._opt_shardings[s])(restored)
                    restored_any = True
                if not restored_any:
                    log_dist(f"checkpoint {tag} has no optimizer states; "
                             "optimizer starts fresh", ranks=[0])
            else:
                log_dist(
                    "pipeline degree changed since save: params restored, "
                    "optimizer state starts fresh (reshape optimizer "
                    "states offline via the universal checkpoint tooling)",
                    ranks=[0])
        return tag, client_state

    @property
    def params(self):
        return self._params

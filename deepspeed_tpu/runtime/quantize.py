"""MoQ — Mixture-of-Quantization training (reference ``runtime/quantize.py:9``).

Progressively quantizes weights DURING training: each matched parameter
carries (start_bits, target_bits, period); every quantizer step past the
period drops one bit and doubles the period (optionally stretched by a
per-block eigenvalue factor — flatter curvature quantizes faster). At
>=3 bits this is group-wise high-bit quantization, 2 bits ternary, 1 bit
binary; ``q_mixed_fp16`` blends the quantized and full-precision weights
while the ratio anneals.

TPU re-design: parameters are immutable pytree leaves, so the per-param
bit state lives in a host-side dict keyed by parameter path, and
``quantize(params, ...)`` returns a new tree (applied by the engine at
gradient-accumulation boundaries, reference engine.py:1921-1930).
"""

import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.functional import quantize_weight
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.patterns import match_name
from deepspeed_tpu.utils.tree import flatten_dots, unflatten_dots


def quantize_ternary(w: jnp.ndarray, num_groups: int = 1) -> jnp.ndarray:
    """2-bit {-a, 0, +a} quantization (reference quantize_tenary): threshold
    at 0.7 * mean|w| per group, alpha = mean |w| over the kept entries."""
    orig = w.shape
    flat = w.reshape(num_groups, -1)
    m = jnp.mean(jnp.abs(flat), axis=1, keepdims=True)
    thres = 0.7 * m
    mask = jnp.abs(flat) > thres
    kept = jnp.sum(jnp.where(mask, jnp.abs(flat), 0.0), axis=1,
                   keepdims=True)
    cnt = jnp.maximum(jnp.sum(mask, axis=1, keepdims=True), 1)
    alpha = kept / cnt
    out = jnp.where(mask, jnp.sign(flat) * alpha, 0.0)
    return out.reshape(orig).astype(w.dtype)


def quantize_binary(w: jnp.ndarray, num_groups: int = 1) -> jnp.ndarray:
    """1-bit sign * mean|w| per group (reference quantize_binary)."""
    orig = w.shape
    flat = w.reshape(num_groups, -1)
    m = jnp.mean(jnp.abs(flat), axis=1, keepdims=True)
    out = jnp.sign(flat) * m
    return out.reshape(orig).astype(w.dtype)


class _ParamQState:
    __slots__ = ("start_bits", "target_bits", "period")

    def __init__(self, start_bits: int, target_bits: int, period: int):
        self.start_bits = start_bits
        self.target_bits = target_bits
        self.period = period


class Quantizer:
    """MoQ driver (reference runtime/quantize.py Quantizer)."""

    def __init__(self, q_groups: int = 1, q_mixed_fp16: bool = False,
                 q_change_ratio: float = 0.01, q_type: str = "symmetric",
                 q_rounding: str = "nearest", q_verbose: bool = False,
                 q_eigenvalue: bool = False, layer_num: int = 0):
        self.q_groups = q_groups
        self.q_mixed_fp16 = q_mixed_fp16
        self.q_change_ratio = q_change_ratio
        self.q_type = q_type
        self.q_rounding = q_rounding
        self.q_verbose = q_verbose
        self.q_eigenvalue = q_eigenvalue
        self.layer_num = layer_num
        self.qsteps = 0
        self.quantize_real_ratio = 1.0
        self._state: Dict[str, _ParamQState] = {}

    @classmethod
    def from_config(cls, qt: Dict[str, Any]) -> "Quantizer":
        """Parse the reference's ``quantize_training`` block shape."""
        bits = qt.get("quantize_bits", {})
        sched = qt.get("quantize_schedule", {})
        algo = qt.get("quantize_algo", {})
        mixed = qt.get("fp16_mixed_quantize", {})
        q = cls(
            q_groups=qt.get("quantize_groups", 1),
            q_mixed_fp16=mixed.get("enabled", False),
            q_change_ratio=mixed.get("quantize_change_ratio", 0.01),
            q_type=algo.get("q_type", "symmetric"),
            q_rounding=algo.get("rounding", "nearest"),
            q_verbose=qt.get("quantize_verbose", False),
            q_eigenvalue=qt.get("eigenvalue", {}).get("enabled", False),
        )
        q._defaults = (
            int(bits.get("start_bits", 16)),
            int(bits.get("target_bits", 8)),
            int(sched.get("quantize_period", 100)),
        )
        q._patterns = qt.get("modules", ["*"])
        return q

    # ------------------------------------------------------------------
    def initialize_bits(self, params, start_bits: int, target_bits: int,
                        period: int, patterns: Optional[List[str]] = None):
        """Attach bit schedules to every matched >=2-D parameter (the
        reference sets start_bits/target_bits attrs on tensors)."""
        patterns = patterns or ["*"]
        for name, leaf in flatten_dots(params).items():
            if getattr(leaf, "ndim", 0) < 2:
                continue
            if match_name(name, patterns):
                self._state[name] = _ParamQState(start_bits, target_bits,
                                                 period)

    def any_precision_switch(self) -> bool:
        return any(s.start_bits != s.target_bits
                   for s in self._state.values())

    def step(self):
        self.qsteps += 1

    def update_fp16_ratio(self):
        if self.q_mixed_fp16:
            self.quantize_real_ratio = max(
                0.0, self.quantize_real_ratio - self.q_change_ratio)

    # ------------------------------------------------------------------
    def compute_quantization(self, w, name: str, factor: int = 1,
                             rng: Optional[jax.Array] = None):
        st = self._state[name]
        if st.start_bits != st.target_bits and self.qsteps >= st.period:
            self.quantize_real_ratio = 1.0
            st.period = (st.period << 1) * factor
            st.start_bits -= 1
            if self.q_verbose:
                logger.info(
                    f"MoQ: {name} -> {st.start_bits} bits at step "
                    f"{self.qsteps}, next period {st.period}")
        assert st.start_bits >= st.target_bits, (
            "quantization bit fell below target precision")

        bits = st.start_bits
        if bits >= 3:
            stochastic = self.q_rounding != "nearest"
            key = None
            if stochastic:
                # per-param, per-step, engine-seeded stream: identical keys
                # across params would correlate rounding errors and break
                # the aggregate unbiasedness of stochastic rounding
                base = rng if rng is not None \
                    else jax.random.PRNGKey(self.qsteps)
                key = jax.random.fold_in(
                    base, hash(name) % (2 ** 31))
            wq = quantize_weight(w, bits, self.q_type,
                                 "stochastic" if stochastic else "nearest",
                                 self.q_groups, key=key)
        elif bits == 2:
            wq = quantize_ternary(w, self.q_groups)
        else:
            wq = quantize_binary(w, self.q_groups)

        if self.q_mixed_fp16 and bits >= st.target_bits - 1:
            wq = (self.quantize_real_ratio * w
                  + (1 - self.quantize_real_ratio) * wq)
        return wq

    def quantize(self, params, overflow: bool = False,
                 eigenvalue_enabled: bool = False,
                 block_eigenvalue: Optional[Dict[str, Tuple[float, int]]]
                 = None, rng: Optional[jax.Array] = None):
        """One MoQ step over the param tree; returns the new tree
        (reference Quantizer.quantize, engine.py:1921-1930 call site)."""
        if overflow and not eigenvalue_enabled:
            return params
        if not self._state:
            if hasattr(self, "_defaults"):
                self.initialize_bits(params, *self._defaults,
                                     patterns=getattr(self, "_patterns",
                                                      None))
            if not self._state:
                return params

        self.step()
        self.update_fp16_ratio()

        flat = flatten_dots(params)
        for name in self._state:
            if name not in flat:
                continue
            factor = 1
            if block_eigenvalue:
                for prefix, (eig, _lid) in block_eigenvalue.items():
                    if name.startswith(prefix) and eig is not None:
                        factor = 1 + math.floor(eig * 4)
                        break
            flat[name] = self.compute_quantization(flat[name], name, factor,
                                                   rng=rng)
        return unflatten_dots(flat)

"""Sparse gradient representation + allreduce
(reference ``runtime/sparse_tensor.py:11`` SparseTensor and the
allgather-based sparse allreduce in ``engine.py:2300-2382``).

Embedding gradients touch only the rows of the tokens in the batch; the
reference ships (indices, values) pairs and allgathers them instead of
reducing the dense [vocab, dim] tensor. Same here, as a pytree-friendly
NamedTuple plus shard_map-ready collectives: ``sparse_allreduce`` allgathers
rows over the axis and scatter-adds locally. Static shapes: the index count
is fixed per batch shape, so XLA compiles one program.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class SparseTensor(NamedTuple):
    indices: jnp.ndarray      # [nnz] int32 row ids
    values: jnp.ndarray       # [nnz, ...] row payloads
    dense_shape: Tuple[int, ...]

    @property
    def sparse_size(self) -> int:
        return int(self.indices.shape[0]) * int(
            jnp.prod(jnp.array(self.values.shape[1:])))

    def to_dense(self) -> jnp.ndarray:
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.indices].add(self.values)


def from_dense_rows(dense: jnp.ndarray, indices: jnp.ndarray) -> SparseTensor:
    """Build a SparseTensor from the given rows of a dense tensor (the
    engine knows which rows a batch touched — its token ids)."""
    return SparseTensor(indices=indices.astype(jnp.int32),
                        values=dense[indices],
                        dense_shape=tuple(dense.shape))


def sparse_allreduce(st: SparseTensor, axis: str) -> SparseTensor:
    """Mean-allreduce of a sparse gradient over mesh axis ``axis``
    (reference sparse_allreduce_no_retain: allgather indices+values, keep
    sparse). Call inside shard_map. Result nnz = world * nnz."""
    k = jax.lax.psum(1, axis)
    all_idx = jax.lax.all_gather(st.indices, axis, axis=0, tiled=True)
    all_val = jax.lax.all_gather(st.values, axis, axis=0, tiled=True)
    return SparseTensor(indices=all_idx, values=all_val / k,
                        dense_shape=st.dense_shape)


def apply_sparse_grad(param: jnp.ndarray, st: SparseTensor,
                      lr: float) -> jnp.ndarray:
    """SGD-style scatter-add application without densifying."""
    return param.at[st.indices].add(-lr * st.values.astype(param.dtype))

"""Checkpoint engine abstraction + default implementation.

Parity with reference ``deepspeed/runtime/checkpoint_engine/checkpoint_engine.py:19``
(CheckpointEngine ABC: create/save/load/commit) and TorchCheckpointEngine.
TPU re-design: state is a JAX pytree; serialization uses flax's msgpack state
dicts (dtype-preserving, incl. bfloat16). Sharded arrays are gathered to host
on save and re-sharded at load by device_put with the current sharding rules —
"save logical, reshard on load" is what makes checkpoints elastic across
mesh-shape changes (the reference needs a whole reshape package for this,
deepspeed/checkpoint/).
"""

import os
import queue
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np
from flax import serialization

from deepspeed_tpu.runtime import checkpoint_manifest as cm
from deepspeed_tpu.utils.logging import log_dist, logger


class CheckpointEngine:
    """ABC surface of the reference checkpoint engine.

    Every ``save()`` between two ``commit()`` calls records the written
    file's size + crc32; ``commit(tag)`` turns the records for the tag's
    directory into a durable ``manifest.json`` — the integrity proof
    ``load_checkpoint`` verifies before trusting the tag."""

    def __init__(self, config_params=None):
        # written by save()/the async writer thread, drained by commit()
        self._manifest_lock = threading.Lock()
        self._manifest_files: Dict[str, Dict[str, Dict[str, object]]] = {}
        # topology block stamped into the next commit's manifests (set by
        # the engine before its saves; see runtime/layout.topology_metadata)
        self._topology_metadata: Optional[Dict[str, Any]] = None
        self.io_retry_count = 0

    def create(self, tag: str):
        log_dist(f"[ckpt] checkpointing tag {tag}", ranks=[0])

    def save(self, state_dict: Dict[str, Any], path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        raise NotImplementedError

    def commit(self, tag: str) -> bool:
        return True

    def pinned_tags(self) -> set:
        """Tags the retention GC must NOT delete right now. Synchronous
        engines have nothing to pin (their writes are durable before
        ``save`` returns); the async engine pins every tag with an
        in-flight write so ``keep_n`` can never delete a directory a
        writer thread is still filling."""
        return set()

    def set_topology_metadata(self, metadata: Optional[Dict[str, Any]]):
        """Attach a topology block (world size, zero stage, axis sizes,
        per-leaf partition specs) to every manifest the next ``commit``
        writes — what lets a later load on a DIFFERENT device count detect
        the mismatch and reshard (runtime/reshard.py) instead of failing."""
        with self._manifest_lock:
            self._topology_metadata = metadata

    # -- manifest bookkeeping -------------------------------------------
    def _record_write(self, path: str, digest: Dict[str, object]):
        d, name = os.path.dirname(path), os.path.basename(path)
        with self._manifest_lock:
            self._manifest_files.setdefault(d, {})[name] = digest

    def _drop_records(self):
        with self._manifest_lock:
            self._manifest_files = {}

    def _commit_manifests(self, tag: str):
        """Write one manifest per recorded TAG directory. Files saved
        outside a ``<tag>``-named dir (e.g. save_16bit_model exports) are
        not part of the tag's integrity contract and are dropped."""
        with self._manifest_lock:
            recorded, self._manifest_files = self._manifest_files, {}
            topology = self._topology_metadata
        for d, files in recorded.items():
            if os.path.basename(d) == str(tag):
                cm.write_manifest(d, tag, files, topology=topology)


def _to_host(tree):
    """Gather device arrays (sharded or not) into host numpy COPIES.

    The copy matters: for leaves that are already host numpy (ZeRO-Offload
    master weights, optimizer moments) ``np.asarray`` would alias the live
    training buffers — an async writer would then serialize memory that CPU
    Adam mutates underneath it (a torn checkpoint)."""
    return jax.tree.map(
        lambda x: np.array(jax.device_get(x), copy=True), tree)


def select_checkpoint_engine(config) -> "CheckpointEngine":
    """Engine selection (reference picks NebulaCheckpointEngine when the
    nebula block is enabled, else TorchCheckpointEngine)."""
    nebula = getattr(config, "nebula", None)
    if nebula is not None and getattr(nebula, "enabled", False):
        return AsyncCheckpointEngine()
    return MsgpackCheckpointEngine()


def _write_atomic(host_state, path: str):
    """Serialize + durably replace ``path`` (shared by sync and async
    engines so durability fixes land in one place): fsync before
    ``os.replace`` and fsync the parent dir after, so a committed tag
    survives power loss; transient OSErrors retry with exponential
    backoff (checkpoint_manifest.retry_io). Returns ``(digest, retries)``
    for manifest recording."""
    payload = serialization.msgpack_serialize(host_state)
    retries = cm.atomic_write_bytes(path, payload)
    return cm.payload_digest(payload), retries


class MsgpackCheckpointEngine(CheckpointEngine):
    """Default engine: flax msgpack files (≈ TorchCheckpointEngine)."""

    def save(self, state_dict: Dict[str, Any], path: str):
        digest, retries = _write_atomic(_to_host(state_dict), path)
        self._record_write(path, digest)
        self.io_retry_count += retries
        log_dist(f"[ckpt] saved {path}", ranks=[0])

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        with open(path, "rb") as f:
            return serialization.msgpack_restore(f.read())

    def commit(self, tag: str) -> bool:
        self._commit_manifests(tag)
        return True


class AsyncCheckpointEngine(CheckpointEngine):
    """Tiered async save (reference NebulaCheckpointEngine's async path,
    ``nebula_checkpoint_engine.py``; same idea as orbax async checkpointing).

    ``save()`` snapshots device state to host SYNCHRONOUSLY (so training may
    mutate buffers immediately after it returns) and hands serialization +
    file IO to one background writer thread. ``commit(tag)`` blocks until
    every pending write for the checkpoint has durably landed — the point
    where the reference engine reports the tag persisted — and surfaces any
    writer error there.
    """

    def __init__(self, config_params=None):
        super().__init__(config_params)
        self._queue: "queue.Queue" = queue.Queue()
        # _errors/_pending cross the writer/caller threads: lock every access
        self._lock = threading.Lock()
        self._errors: list = []
        self._pending: list = []
        # tag -> number of in-flight writes into that tag's directory.
        # This is what pinned_tags() reads; _pending alone cannot serve,
        # because wait() POPS it — a retention GC racing a concurrent
        # wait() would see an empty pending list while writes are still
        # on the queue and delete the very tag being written.
        self._inflight_tags: Dict[str, int] = {}
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()

    @staticmethod
    def _tag_of(path: str) -> str:
        """Checkpoint files live at ``<save_dir>/<tag>/<file>``: the
        tag is the parent directory's basename."""
        return os.path.basename(os.path.dirname(path))

    def _drain(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            host_state, path, done = item
            try:
                digest, retries = _write_atomic(host_state, path)
                self._record_write(path, digest)
                self.io_retry_count += retries
                log_dist(f"[ckpt] async saved {path}", ranks=[0])
            except Exception as e:  # surfaced at commit()
                with self._lock:
                    self._errors.append((path, e))
            finally:
                # unpin BEFORE signalling done: once a waiter wakes the
                # GC may run, and it must already see the updated pins
                tag = self._tag_of(path)
                with self._lock:
                    count = self._inflight_tags.get(tag, 0) - 1
                    if count > 0:
                        self._inflight_tags[tag] = count
                    else:
                        self._inflight_tags.pop(tag, None)
                done.set()

    def save(self, state_dict: Dict[str, Any], path: str):
        # snapshot-and-enqueue UNCONDITIONALLY: an earlier write failure
        # must not silently drop later files — every failure is
        # accumulated and surfaced together at commit()/load()
        host_state = _to_host(state_dict)  # consistent snapshot, blocking
        done = threading.Event()
        tag = self._tag_of(path)
        with self._lock:
            self._pending.append(done)
            self._inflight_tags[tag] = self._inflight_tags.get(tag, 0) + 1
        self._queue.put((host_state, path, done))

    def pinned_tags(self) -> set:
        with self._lock:
            return set(self._inflight_tags)

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        self.wait()  # never read a file a pending write may still replace
        self._raise_errors()  # a failed write leaves a STALE file behind
        with open(path, "rb") as f:
            return serialization.msgpack_restore(f.read())

    def wait(self):
        with self._lock:
            pending, self._pending = self._pending, []
        for done in pending:
            done.wait()

    def _raise_errors(self):
        with self._lock:
            errors, self._errors = self._errors, []
        if errors:
            # the tag is invalid: its successful files must not be
            # certified by a manifest at the next commit
            self._drop_records()
            paths = ", ".join(p for p, _ in errors)
            raise RuntimeError(
                f"async checkpoint write failed for {len(errors)} "
                f"file(s): {paths}") from errors[0][1]

    def commit(self, tag: str) -> bool:
        self.wait()
        self._raise_errors()
        self._commit_manifests(tag)
        log_dist(f"[ckpt] tag {tag} committed (all async writes durable)",
                 ranks=[0])
        return True

"""Checkpoint engine abstraction + default implementation.

Parity with reference ``deepspeed/runtime/checkpoint_engine/checkpoint_engine.py:19``
(CheckpointEngine ABC: create/save/load/commit) and TorchCheckpointEngine.
TPU re-design: state is a JAX pytree; serialization uses flax's msgpack state
dicts (dtype-preserving, incl. bfloat16). Sharded arrays are gathered to host
on save and re-sharded at load by device_put with the current sharding rules —
"save logical, reshard on load" is what makes checkpoints elastic across
mesh-shape changes (the reference needs a whole reshape package for this,
deepspeed/checkpoint/).
"""

import os
from typing import Any, Dict, Optional

import jax
import numpy as np
from flax import serialization

from deepspeed_tpu.utils.logging import log_dist, logger


class CheckpointEngine:
    """ABC surface of the reference checkpoint engine."""

    def __init__(self, config_params=None):
        pass

    def create(self, tag: str):
        log_dist(f"[ckpt] checkpointing tag {tag}", ranks=[0])

    def save(self, state_dict: Dict[str, Any], path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        raise NotImplementedError

    def commit(self, tag: str) -> bool:
        return True


def _to_host(tree):
    """Gather device arrays (sharded or not) into host numpy."""
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)


class MsgpackCheckpointEngine(CheckpointEngine):
    """Default engine: flax msgpack files (≈ TorchCheckpointEngine)."""

    def save(self, state_dict: Dict[str, Any], path: str):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        host_state = _to_host(state_dict)
        payload = serialization.msgpack_serialize(host_state)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
        log_dist(f"[ckpt] saved {path}", ranks=[0])

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        with open(path, "rb") as f:
            return serialization.msgpack_restore(f.read())

    def commit(self, tag: str) -> bool:
        return True

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (  # noqa: F401
    CurriculumScheduler,
    truncate_batch_to_difficulty,
)

"""Curriculum learning scheduler (reference
``runtime/data_pipeline/curriculum_scheduler.py:8``).

Maps global step -> difficulty (typically sequence length). Schedules:
``fixed_linear``, ``fixed_root``, ``fixed_discrete``, ``custom``. The
engine injects the current difficulty as a ``curriculum_seqlen`` kwarg
(reference engine.py:1657-1663); models that scan over tokens can also use
it to slice the batch (static shapes per difficulty value — XLA compiles
one program per distinct seqlen, so use difficulty_step to quantize).
"""

import math
from typing import Any, Callable, Dict, Optional


class CurriculumScheduler:
    def __init__(self, config):
        """``config`` is a CurriculumConfig or a raw dict with the
        reference's keys."""
        if isinstance(config, dict):
            get = config.get
        else:
            get = lambda k, d=None: getattr(config, k, d)  # noqa: E731
        self.curriculum_type = get("curriculum_type", "seqlen")
        self.min_difficulty = int(get("min_difficulty", 1))
        self.max_difficulty = int(get("max_difficulty", 1024))
        self.schedule_type = get("schedule_type", "fixed_linear")
        self.schedule_config: Dict[str, Any] = dict(
            get("schedule_config", {}) or {})
        self.custom_get_difficulty: Optional[Callable[[int], int]] = None
        self.current_difficulty = self.min_difficulty

        if self.schedule_type in ("fixed_linear", "fixed_root"):
            if "total_curriculum_step" not in self.schedule_config:
                raise ValueError(
                    f"{self.schedule_type} schedule needs "
                    f"total_curriculum_step in schedule_config")
            if int(self.schedule_config.get("difficulty_step", 1)) < 8:
                from deepspeed_tpu.utils.logging import logger

                logger.warning(
                    "curriculum difficulty_step < 8: every distinct "
                    "difficulty value compiles a separate XLA program; "
                    "set schedule_config.difficulty_step to a multiple of "
                    "8 to bound recompiles")
        if self.schedule_type == "fixed_discrete":
            need = {"difficulty", "max_step"}
            if not need.issubset(self.schedule_config):
                raise ValueError(
                    "fixed_discrete schedule needs difficulty and max_step "
                    "lists")
            d = self.schedule_config["difficulty"]
            s = self.schedule_config["max_step"]
            if len(s) != len(d) - 1:
                raise ValueError(
                    "max_step must have one fewer entry than difficulty")

    def set_custom_get_difficulty(self, fn: Callable[[int], int]):
        self.custom_get_difficulty = fn

    def _quantize(self, difficulty: float) -> int:
        step = int(self.schedule_config.get("difficulty_step", 1))
        d = int(difficulty) // step * step
        return max(min(d, self.max_difficulty), self.min_difficulty)

    def get_difficulty(self, global_steps: int) -> int:
        sc = self.schedule_config
        if self.schedule_type == "custom":
            if self.custom_get_difficulty is None:
                raise ValueError(
                    "custom schedule requires set_custom_get_difficulty")
            return self.custom_get_difficulty(global_steps)
        if self.schedule_type == "fixed_discrete":
            levels = sc["difficulty"]
            bounds = sc["max_step"]
            for level, bound in zip(levels, bounds):
                if global_steps <= bound:
                    return int(level)
            return int(levels[-1])
        total = int(sc["total_curriculum_step"])
        frac = min(global_steps / max(total, 1), 1.0)
        if self.schedule_type == "fixed_root":
            frac = frac ** (1.0 / float(sc.get("root_degree", 2)))
        elif self.schedule_type != "fixed_linear":
            raise ValueError(
                f"unknown curriculum schedule {self.schedule_type!r}")
        span = self.max_difficulty - self.min_difficulty
        return self._quantize(self.min_difficulty + span * frac)

    def update_difficulty(self, global_steps: int) -> int:
        self.current_difficulty = self.get_difficulty(global_steps)
        return self.current_difficulty

    def get_current_difficulty(self) -> int:
        return self.current_difficulty

    def state_dict(self) -> Dict[str, Any]:
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd: Dict[str, Any]):
        self.current_difficulty = sd["current_difficulty"]


def truncate_batch_to_difficulty(batch, seqlen: int):
    """Truncate every [B, T, ...] sequence tensor in a batch dict to the
    scheduled seqlen difficulty — the one curriculum transform both the
    dense and pipeline engines apply (reference engine.py:1629 curriculum
    setup is engine-agnostic; one compiled program per distinct value)."""
    return {
        k: (v[:, :seqlen]
            if getattr(v, "ndim", 0) >= 2 and v.shape[1] > seqlen
            else v)
        for k, v in batch.items()
    }

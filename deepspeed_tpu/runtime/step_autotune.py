"""Step-config autotuner: HBM-bounded (remat_policy, micro_batch, flash)
search for a model config and device (ROADMAP item 3).

The search space is the one the 1.3B plateau analysis exposed: which
activations to keep (``_remat_policy`` in ``models/transformer_lm.py``),
how large a micro batch the remaining HBM headroom buys, and whether the
flash kernel replaces dense attention. Candidates are **pruned
analytically first**: every candidate's full train step (fwd + bwd +
optimizer tail) is AOT-lowered from avals only — the
``benchmarks/memory_report.py`` pattern, no parameter ever materializes —
and its ``memory_analysis()`` peak working set is checked against the
``DEVICE_HBM_GIB`` ceiling (``telemetry/memory.py``). A candidate over
the ceiling is **never executed**, so the search cannot OOM a real
device. Survivors are then live-benchmarked (fenced wall-clock + the
step profiler's analytic-MFU arithmetic: XLA cost-analysis FLOPs over
measured time over the ``HW_PEAK_BF16_TFLOPS`` table) when a backend
that can run them is present, and scored by a calibrated roofline
prediction when it is not (searching a v4/v5e config from a CPU host).

Resolution order for :func:`get_step_config` — the exact
mem -> disk -> PRETUNED -> live chain of ``ops/pallas/autotune.py``:

1. in-memory cache (one lookup per process per key)
2. on-disk JSON cache — ``$DS_TPU_STEP_AUTOTUNE_CACHE`` or
   ``~/.cache/deepspeed_tpu/step_configs.json``, keyed
   ``device_kind|nN|model|seq|dtype`` (N = device count, so an elastic
   topology change re-tunes); corrupt files warn once and fall
   through, overwritten by the next tuned write.
3. shipped :data:`PRETUNED` table — seeds from the committed
   ``benchmarks/mfu_search_results.json`` search artifact.
4. live search, IF enabled (``autotune=True`` or
   ``DS_TPU_STEP_AUTOTUNE=1``): runs :func:`search` and persists the
   winner to (2).
5. ``None`` — the engine keeps its configured settings unchanged.

Every cached/pretuned entry is re-validated (:func:`_valid`) before use:
the remat policy must resolve through ``_remat_policy`` and the micro
batch must be a positive int, so a stale or hand-edited cache can never
push an invalid config into the engine.
"""

import dataclasses
import json
import os
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

_CACHE_ENV = "DS_TPU_STEP_AUTOTUNE_CACHE"
_AUTOTUNE_ENV = "DS_TPU_STEP_AUTOTUNE"

# Spec HBM bandwidth per jax device in GB/s — the roofline's memory term.
# Same keying/ordering convention as DEVICE_HBM_GIB (first substring
# match wins; v2/v3 per-core). Sources: Google TPU system-architecture
# pages. No CPU entry: predictions for a CPU target are not meaningful.
DEVICE_HBM_GBPS = (
    ("v6e", 1640.0),
    ("v6 lite", 1640.0),
    ("v5p", 2765.0),
    ("v5e", 819.0),
    ("v5 lite", 819.0),
    ("v5", 2765.0),
    ("v4", 1228.0),
    ("v3", 450.0),
    ("v2", 350.0),
)

# Measured anchor for roofline calibration: the r4 1.3B seq-1024 bench
# (flash + full remat + micro 6 on one v5e chip) hit 104.08 analytic
# TFLOPS. ``calibrate_compute_efficiency`` solves the additive roofline
# t = F/(c*peak) + B/bw for the compute-efficiency fraction c at this
# point, so predictions are pinned to a real measurement rather than to
# the marketing peak.
CALIBRATION_ANCHOR = {
    "model": "gpt2-1.3b", "seq": 1024, "micro_batch": 6,
    "remat_policy": "full", "flash": True,
    "measured_analytic_tflops": 104.08, "device_kind": "TPU v5e",
}
_DEFAULT_COMPUTE_EFF = 0.55  # fallback c when no anchor fits the solve

# (device_kind, model, seq, dtype) -> winner entry. Seeds from the
# committed search artifact (benchmarks/mfu_search_results.json): on
# v4/v5p the winner is flash + full remat at micro 8 — selective
# policies self-defeat at this scale (save_dots' dense bound busts v4's
# 32 GiB from micro 6 up, and where it fits its extra held activations
# buy less MFU than a bigger micro batch does). The v5e rows are the
# *benched* reality from gpt_pretrain.py (flash + full remat + micro 6
# measured on chip; micro 7/8 and every selective policy OOM the
# 16 GiB ceiling). A live search (DS_TPU_STEP_AUTOTUNE=1) overwrites
# these via the disk cache.
PRETUNED: Dict[Tuple[str, str, int, str], Dict[str, Any]] = {}
for _kind in ("TPU v4", "TPU v5p"):
    PRETUNED[(_kind, "gpt2-1.3b", 1024, "bfloat16")] = {
        "remat_policy": "full", "micro_batch": 8, "flash": True}
for _kind in ("TPU v5 lite", "TPU v5e"):
    PRETUNED[(_kind, "gpt2-1.3b", 1024, "bfloat16")] = {
        "remat_policy": "full", "micro_batch": 6, "flash": True}

_lock = threading.Lock()
_mem_cache: Dict[str, Dict[str, Any]] = {}
_disk_warned = False


@dataclasses.dataclass(frozen=True)
class StepCandidate:
    """One point of the search space."""

    remat_policy: str
    micro_batch: int
    flash: Any  # True | False (never "auto": the search decides)

    def label(self) -> str:
        return (f"{self.remat_policy}/micro{self.micro_batch}/"
                f"{'flash' if self.flash else 'dense'}")


# ---------------------------------------------------------------------------
# cache plumbing (the ops/pallas/autotune.py pattern)
# ---------------------------------------------------------------------------

def cache_path() -> str:
    return os.environ.get(_CACHE_ENV) or os.path.join(
        os.path.expanduser("~"), ".cache", "deepspeed_tpu",
        "step_configs.json")


def cache_key(device_kind: str, model: str, seq: int, dtype,
              num_devices: int = 1) -> str:
    """``device_kind|nN|model|seq|dtype`` — the device COUNT is part of the
    key so an elastic resume on a shrunk/grown slice re-tunes instead of
    reusing the old topology's remat×micro winner (the HBM headroom and
    per-device batch landscape both move with N)."""
    import jax.numpy as jnp

    return (f"{device_kind}|n{int(num_devices)}|{model}|{int(seq)}|"
            f"{jnp.dtype(dtype).name}")


def _load_disk_cache() -> Dict[str, Dict[str, Any]]:
    global _disk_warned
    path = cache_path()
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            raise ValueError(f"expected a JSON object, got {type(data)}")
        return data
    except (OSError, ValueError) as e:
        if not _disk_warned:
            _disk_warned = True
            warnings.warn(
                f"ignoring corrupt step-autotune cache {path!r} ({e}); "
                "falling back to pretuned/live resolution — the next "
                "search rewrites it", RuntimeWarning)
        return {}


def _store_disk_cache(key: str, entry: Dict[str, Any]) -> None:
    path = cache_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    data = _load_disk_cache()
    data[key] = entry
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def _valid(entry) -> Optional[Dict[str, Any]]:
    """Sanity-check a cached/pretuned winner before it reaches the engine:
    the policy must resolve through ``_remat_policy`` and the micro batch
    must be a positive int. Returns a normalized copy or None."""
    if not isinstance(entry, dict):
        return None
    from deepspeed_tpu.models.transformer_lm import _remat_policy

    try:
        policy = str(entry["remat_policy"])
        _remat_policy(policy)  # raises ValueError on unknown names
        micro = int(entry["micro_batch"])
        flash = bool(entry["flash"])
    except (KeyError, TypeError, ValueError):
        return None
    if micro < 1:
        return None
    out = dict(entry)
    out.update(remat_policy=policy, micro_batch=micro, flash=flash)
    return out


def clear_memory_cache() -> None:
    """Test hook: drop the per-process memoization (disk cache untouched)."""
    global _disk_warned
    with _lock:
        _mem_cache.clear()
        _disk_warned = False


def model_key(cfg) -> str:
    """Stable model identity for cache keys: the GPT2_SIZES name when the
    trunk dimensions match a named size, else a dimensions signature."""
    from deepspeed_tpu.models.transformer_lm import GPT2_SIZES

    for name, dims in GPT2_SIZES.items():
        if all(getattr(cfg, k, None) == v for k, v in dims.items()):
            return name
    return (f"gpt-l{cfg.n_layer}-d{cfg.n_embd}-h{cfg.n_head}"
            f"-v{cfg.vocab_size}")


# ---------------------------------------------------------------------------
# device tables
# ---------------------------------------------------------------------------

def _table_lookup(table, kind: str) -> Optional[float]:
    kind = (kind or "").lower()
    for sub, val in table:
        if sub in kind:
            return val
    return None


def device_ceiling_bytes(device_kind: Optional[str] = None,
                         override_gib: Optional[float] = None
                         ) -> Tuple[Optional[int], str]:
    """HBM ceiling for a *named* target device — unlike
    ``telemetry.memory.hbm_bytes`` this never needs a backend, so a CPU
    host can run the search against a v4/v5e ceiling."""
    from deepspeed_tpu.telemetry.memory import DEVICE_HBM_GIB, hbm_bytes

    if override_gib:
        return int(override_gib * 1024 ** 3), "config override"
    if device_kind:
        gib = _table_lookup(DEVICE_HBM_GIB, device_kind)
        if gib is not None:
            return int(gib * 1024 ** 3), f"table[{device_kind}]"
        return None, f"no HBM table entry for {device_kind!r}"
    return hbm_bytes()


def device_peak_and_bw(device_kind: str) -> Tuple[Optional[float],
                                                  Optional[float]]:
    """(peak bf16 TFLOPS, HBM GB/s) for a named device kind, or Nones."""
    from deepspeed_tpu.profiling.step_profiler import HW_PEAK_BF16_TFLOPS

    return (_table_lookup(HW_PEAK_BF16_TFLOPS, device_kind),
            _table_lookup(DEVICE_HBM_GBPS, device_kind))


# ---------------------------------------------------------------------------
# analytic pruning: avals-only AOT lowering (benchmarks/memory_report.py)
# ---------------------------------------------------------------------------

def _build_model(model: str, seq: int, dtype, cand: StepCandidate,
                 model_overrides: Optional[Dict[str, Any]] = None):
    from deepspeed_tpu.models.transformer_lm import GPT, gpt2_config

    overrides = dict(model_overrides or {})
    cfg = gpt2_config(
        model, n_positions=seq, dtype=dtype, param_dtype=dtype,
        scan_layers=True, remat=True, remat_policy=cand.remat_policy,
        use_flash_attention=cand.flash, **overrides)
    return GPT(cfg)


def _make_tx():
    # the benched pure-bf16 recipe (gpt_pretrain.py / memory_report.py):
    # moments inherit the bf16 param dtype, no fp32 masters
    import optax

    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(2e-4, b1=0.9, b2=0.95, weight_decay=0.1))


def _build_step(model, tx):
    import jax
    import optax

    def train_step(params, opt_state, batch, rng):
        def loss_fn(p):
            return model.apply(p, batch["input_ids"],
                               labels=batch["labels"],
                               deterministic=False,
                               rngs={"dropout": rng})

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(train_step, donate_argnums=(0, 1))


def analyze_candidate(model: str, seq: int, dtype, cand: StepCandidate,
                      model_overrides: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, float]:
    """AOT memory + cost analysis of one candidate's full train step from
    avals only — nothing executes, nothing materializes. Returns the
    ``compiled_memory_analysis`` dict merged with XLA cost metrics."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.profiling.flops_profiler.profiler import cost_analysis
    from deepspeed_tpu.telemetry.memory import compiled_memory_analysis

    m = _build_model(model, seq, dtype, cand, model_overrides)
    ids = jax.ShapeDtypeStruct((cand.micro_batch, seq), jnp.int32)
    batch = {"input_ids": ids, "labels": ids}
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    params = jax.eval_shape(m.init, rng, ids)
    tx = _make_tx()
    opt_state = jax.eval_shape(tx.init, params)
    step = _build_step(m, tx)
    # one compile serves both reads: the second lower() is a cache hit
    mem = compiled_memory_analysis(step, params, opt_state, batch, rng)
    cost = cost_analysis(step, params, opt_state, batch, rng)
    out = dict(mem)
    out.update(cost)
    return out


# ---------------------------------------------------------------------------
# roofline prediction (CPU host searching for a TPU target)
# ---------------------------------------------------------------------------

def calibrate_compute_efficiency(anchor_flops: float, anchor_bytes: float
                                 ) -> Tuple[float, str]:
    """Solve t = F/(c*peak) + B/bw for c at the measured anchor point
    (``CALIBRATION_ANCHOR``). The anchor's F/B come from the SAME analytic
    pipeline that scores candidates, so the calibration and the
    predictions share every modeling bias. Clamped to (0, 1]."""
    a = CALIBRATION_ANCHOR
    peak, bw = device_peak_and_bw(a["device_kind"])
    if not (peak and bw and anchor_flops > 0):
        return _DEFAULT_COMPUTE_EFF, "default (no anchor tables)"
    t_meas = anchor_flops / (a["measured_analytic_tflops"] * 1e12)
    t_mem = anchor_bytes / (bw * 1e9)
    t_compute = t_meas - t_mem
    if t_compute <= 0:  # anchor claims memory-bound: solve degenerates
        return _DEFAULT_COMPUTE_EFF, "default (anchor memory-bound)"
    c = anchor_flops / (peak * 1e12 * t_compute)
    c = max(0.01, min(1.0, c))
    return c, (f"solved at {a['model']} seq{a['seq']} "
               f"micro{a['micro_batch']} flash on {a['device_kind']} = "
               f"{a['measured_analytic_tflops']} TFLOPS")


def predict_step(flops: float, bytes_accessed: float, device_kind: str,
                 compute_eff: float) -> Dict[str, float]:
    """Additive-roofline step-time/MFU prediction for a target device:
    t = F/(c*peak) + B/bw; predicted analytic MFU = F/(t*peak)."""
    peak, bw = device_peak_and_bw(device_kind)
    if not (peak and bw and flops > 0):
        return {}
    t_compute = flops / (compute_eff * peak * 1e12)
    t_memory = bytes_accessed / (bw * 1e9)
    t = t_compute + t_memory
    tflops = flops / t / 1e12
    return {
        "predicted_step_s": t,
        # where the predicted time goes — the roofline's two terms
        "predicted_compute_s": t_compute,
        "predicted_memory_s": t_memory,
        "predicted_analytic_tflops": round(tflops, 2),
        "predicted_analytic_mfu": round(tflops / peak, 4),
    }


# ---------------------------------------------------------------------------
# live benchmark (the step profiler's analytic-MFU arithmetic)
# ---------------------------------------------------------------------------

def live_benchmark(model: str, seq: int, dtype, cand: StepCandidate,
                   model_overrides: Optional[Dict[str, Any]] = None,
                   steps: int = 3, warmup: int = 1,
                   measure_fused: bool = True) -> Dict[str, Any]:
    """Execute one candidate's real train step and measure it: fenced
    wall-clock over ``steps`` iterations, XLA cost-analysis FLOPs of the
    compiled program, and analytic MFU against the hardware peak table —
    the identical arithmetic the step profiler reports. With
    ``measure_fused`` the optimizer tail is also timed as a separate
    program (the two-program fwd/bwd + apply split) so the winner records
    whether fusing the tail into the step pays wall-clock."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from deepspeed_tpu.profiling.flops_profiler.profiler import cost_analysis
    from deepspeed_tpu.profiling.step_profiler import peak_tflops

    m = _build_model(model, seq, dtype, cand, model_overrides)
    rng = jax.random.PRNGKey(0)
    r = np.random.RandomState(0)
    vocab = m.config.vocab_size
    ids = jnp.asarray(r.randint(0, vocab, (cand.micro_batch, seq)),
                      jnp.int32)
    batch = {"input_ids": ids, "labels": ids}
    params = m.init(rng, ids)
    tx = _make_tx()
    opt_state = tx.init(params)
    step = _build_step(m, tx)
    rng2 = jax.random.PRNGKey(1)

    def timed(fn, *args, n=steps):
        out = fn(*args)  # compile + warm (donated args: use fresh copies)
        jax.block_until_ready(out)
        return out

    # fused single-program timing: donation consumes the state, so thread
    # it through the loop exactly as training would
    p, o = params, opt_state
    p, o, _ = timed(step, p, o, batch, rng2)
    for _ in range(max(0, warmup - 1)):
        p, o, _ = step(p, o, batch, rng2)
        jax.block_until_ready(p)
    t0 = time.perf_counter()
    for _ in range(steps):
        p, o, loss = step(p, o, batch, rng2)
    jax.block_until_ready(p)
    fused_s = (time.perf_counter() - t0) / steps

    cost = cost_analysis(step, jax.eval_shape(lambda: p),
                         jax.eval_shape(lambda: o), batch, rng2)
    peak, peak_src = peak_tflops()
    tflops = cost["flops"] / fused_s / 1e12 if fused_s > 0 else 0.0
    out: Dict[str, Any] = {
        "measured_step_s": fused_s,
        "flops_per_step": cost["flops"],
        "bytes_accessed_per_step": cost["bytes_accessed"],
        "analytic_tflops": round(tflops, 3),
        "analytic_mfu": round(tflops / peak, 5) if peak else 0.0,
        "peak_tflops": peak,
        "peak_source": peak_src,
        "loss": float(loss),
    }

    if measure_fused:
        # two-program split: grads program + optimizer-tail program, the
        # engine's forward()/step() shape (no donation reuse across them)
        def grads_fn(params, batch, rng):
            def loss_fn(pp):
                return m.apply(pp, batch["input_ids"],
                               labels=batch["labels"],
                               deterministic=False, rngs={"dropout": rng})

            return jax.value_and_grad(loss_fn)(params)

        def apply_fn(params, opt_state, grads):
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        jg = jax.jit(grads_fn)
        ja = jax.jit(apply_fn, donate_argnums=(0, 1))
        _, g = jg(p, batch, rng2)
        jax.block_until_ready(g)
        p2, o2 = ja(p, o, g)
        jax.block_until_ready(p2)
        t0 = time.perf_counter()
        for _ in range(steps):
            _, g = jg(p2, batch, rng2)
            p2, o2 = ja(p2, o2, g)
        jax.block_until_ready(p2)
        split_s = (time.perf_counter() - t0) / steps
        out["unfused_step_s"] = split_s
        out["fused_saving_s"] = split_s - fused_s
        out["fuse_optimizer"] = bool(fused_s < split_s)
    return out


# ---------------------------------------------------------------------------
# the search
# ---------------------------------------------------------------------------

DEFAULT_POLICIES = ("full", "selective", "save_dots",
                    "save_nothing_but_flash")


def candidate_grid(micro_batches: Sequence[int],
                   policies: Sequence[str] = DEFAULT_POLICIES,
                   flash_options: Sequence[bool] = (True, False)
                   ) -> List[StepCandidate]:
    """The cross product, minus points that alias each other:
    ``save_nothing_but_flash`` without flash IS ``full`` (no tensor
    carries the saved names on the einsum path)."""
    out = []
    for pol in policies:
        for flash in flash_options:
            if pol == "save_nothing_but_flash" and not flash:
                continue
            for mb in micro_batches:
                out.append(StepCandidate(pol, int(mb), bool(flash)))
    return out


def search(model: str = "gpt2-1.3b", seq: int = 1024, dtype=None, *,
           micro_batches: Sequence[int] = (4, 6, 8),
           policies: Sequence[str] = DEFAULT_POLICIES,
           flash_options: Sequence[bool] = (True, False),
           device_kind: Optional[str] = None,
           hbm_override_gib: Optional[float] = None,
           live: Optional[bool] = None,
           live_steps: int = 3,
           measure_fused: bool = True,
           model_overrides: Optional[Dict[str, Any]] = None,
           baseline: Optional[StepCandidate] = None,
           _analyze=None, _bench=None) -> Dict[str, Any]:
    """Run the full HBM-bounded search and return the report.

    Per candidate: avals-only AOT analysis -> predicted peak bytes ->
    analytic prune against the device ceiling -> (surviving candidates
    only) live benchmark when ``live`` — default: live iff the target
    device is the one actually attached. ``_analyze``/``_bench`` inject
    fakes for tests. Nothing over the ceiling is ever executed.
    """
    import jax
    import jax.numpy as jnp

    dtype = dtype or jnp.bfloat16
    attached = ""
    try:
        attached = jax.devices()[0].device_kind
    except Exception:
        pass
    target = device_kind or attached or "cpu"
    ceiling, ceiling_src = device_ceiling_bytes(target, hbm_override_gib)
    if live is None:
        live = bool(attached) and (target.lower() in attached.lower()
                                   or attached.lower() in target.lower())
    analyze = _analyze or (
        lambda c: analyze_candidate(model, seq, dtype, c, model_overrides))
    bench = _bench or (
        lambda c: live_benchmark(model, seq, dtype, c, model_overrides,
                                 steps=live_steps,
                                 measure_fused=measure_fused))

    base = baseline or StepCandidate("full", micro_batches[0] if 6 not in
                                     micro_batches else 6, False)
    cands = candidate_grid(micro_batches, policies, flash_options)
    if base not in cands:
        cands.insert(0, base)

    # pass 1 — every candidate gets the avals-only AOT treatment (memory
    # breakdown + XLA cost metrics); nothing executes here
    rows: List[Dict[str, Any]] = []
    analyses: List[Optional[Dict[str, float]]] = []
    for cand in cands:
        row: Dict[str, Any] = {
            "remat_policy": cand.remat_policy,
            "micro_batch": cand.micro_batch,
            "flash": cand.flash,
            "is_baseline": cand == base,
            "executed_live": False,
        }
        try:
            an = analyze(cand)
        except Exception as e:  # a candidate that cannot even lower loses
            row.update(error=f"{type(e).__name__}: {e}", fits=False)
            an = None
        if an is not None:
            peak_b = an["peak_working_set_bytes"]
            row["predicted_peak_bytes"] = peak_b
            row["analysis"] = {
                k: an[k] for k in
                ("argument_bytes", "temp_bytes", "alias_bytes",
                 "flops", "bytes_accessed") if k in an}
            row["fits"] = bool(peak_b < ceiling) if ceiling else None
        rows.append(row)
        analyses.append(an)

    # calibrate the roofline on the anchor candidate (the measured r4
    # flash/full/micro-6 point) when this search covers it; else default
    a = CALIBRATION_ANCHOR
    anchor = StepCandidate(a["remat_policy"], a["micro_batch"], a["flash"])
    compute_eff, calib_src = _DEFAULT_COMPUTE_EFF, "default (no anchor run)"
    if model == a["model"] and seq == a["seq"] and anchor in cands:
        an = analyses[cands.index(anchor)]
        if an is not None:
            compute_eff, calib_src = calibrate_compute_efficiency(
                an.get("flops", 0.0), an.get("bytes_accessed", 0.0))

    # pass 2 — roofline predictions for everyone; live benchmark ONLY for
    # candidates whose predicted peak clears the ceiling
    for cand, row, an in zip(cands, rows, analyses):
        if an is None:
            continue
        row.update(predict_step(an.get("flops", 0.0),
                                an.get("bytes_accessed", 0.0), target,
                                compute_eff))
        if live and row["fits"] is not False:
            try:
                row.update(bench(cand))
                row["executed_live"] = True
            except Exception as e:
                row["live_error"] = f"{type(e).__name__}: {e}"

    def score(r):
        # measured MFU outranks predicted; candidates with neither sink
        if r.get("error") or r["fits"] is False:
            return -1.0
        return r.get("analytic_mfu") or r.get("predicted_analytic_mfu") \
            or 0.0

    base_row = next(r for r in rows if r["is_baseline"])
    winner = max(rows, key=score)
    report = {
        "model": model, "seq": seq,
        "dtype": jnp.dtype(dtype).name,
        "device_kind": target,
        "backend_device": attached or "none",
        "hbm_ceiling_bytes": ceiling,
        "hbm_ceiling_source": ceiling_src,
        "compute_efficiency": compute_eff,
        "calibration": calib_src,
        "live": bool(live),
        "candidates": rows,
        "baseline": {k: base_row.get(k) for k in
                     ("remat_policy", "micro_batch", "flash",
                      "predicted_peak_bytes", "predicted_analytic_mfu",
                      "analytic_mfu")},
        "winner": winner,
        "winner_beats_baseline": score(winner) > score(base_row),
    }
    return report


def winner_entry(report: Dict[str, Any]) -> Dict[str, Any]:
    """Compress a search report's winner into a cacheable entry."""
    w = report["winner"]
    entry = {k: w[k] for k in ("remat_policy", "micro_batch", "flash")}
    for k in ("predicted_peak_bytes", "predicted_analytic_mfu",
              "analytic_mfu", "measured_step_s", "fuse_optimizer"):
        if w.get(k) is not None:
            entry[k] = w[k]
    entry["device_kind"] = report["device_kind"]
    return entry


# ---------------------------------------------------------------------------
# resolution (mem -> disk -> PRETUNED -> live)
# ---------------------------------------------------------------------------

def get_step_config(model: str, seq: int, dtype=None, *,
                    device_kind: Optional[str] = None,
                    num_devices: Optional[int] = None,
                    autotune: Optional[bool] = None,
                    search_kwargs: Optional[Dict[str, Any]] = None
                    ) -> Optional[Dict[str, Any]]:
    """Resolve the tuned (remat_policy, micro_batch, flash) for a model
    config on a device, or None (caller keeps its configured settings).

    ``autotune=None`` defers to the ``DS_TPU_STEP_AUTOTUNE`` env flag;
    ``search_kwargs`` feeds the live :func:`search` on a miss.
    ``num_devices`` keys the cache (default: the visible device count) —
    a topology change misses the old entry and re-resolves. PRETUNED
    entries stay per-chip (micro_batch is per device), so they remain the
    fallback at any count.
    """
    import jax
    import jax.numpy as jnp

    dtype = dtype or jnp.bfloat16
    if device_kind is None:
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None
    if num_devices is None:
        try:
            num_devices = jax.device_count()
        except Exception:
            num_devices = 1
    key = cache_key(device_kind, model, seq, dtype, num_devices)

    with _lock:
        hit = _mem_cache.get(key)
        if hit is not None:
            return dict(hit)
        entry = _valid(_load_disk_cache().get(key))
        if entry is not None:
            entry.setdefault("source", "disk")
            _mem_cache[key] = entry
            return dict(entry)
        pre = _valid(PRETUNED.get(
            (device_kind, model, int(seq), jnp.dtype(dtype).name)))
        if pre is not None:
            pre.setdefault("source", "pretuned")
            _mem_cache[key] = pre
            return dict(pre)

    if autotune is None:
        autotune = os.environ.get(_AUTOTUNE_ENV, "0") not in ("", "0")
    if not autotune:
        return None

    report = search(model, seq, dtype, device_kind=device_kind,
                    **(search_kwargs or {}))
    tuned = winner_entry(report)
    tuned["source"] = "live"
    # Persist WITHOUT "source" — a later process loading this entry saw a
    # disk hit, not a live search, and reports it as such.
    persisted = {k: v for k, v in tuned.items() if k != "source"}
    with _lock:
        _mem_cache[key] = tuned
        try:
            _store_disk_cache(key, persisted)
        except OSError as e:
            warnings.warn(
                f"step autotune: could not persist winner to "
                f"{cache_path()!r} ({e}); it stays in-memory for this "
                "process", RuntimeWarning)
    return dict(tuned)

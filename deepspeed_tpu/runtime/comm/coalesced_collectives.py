"""Coalesced collectives.

Parity with reference ``runtime/comm/coalesced_collectives.py:30``
``reduce_scatter_coalesced``: ZeRO's gradient path reduces MANY tensors of
ragged sizes in ONE collective by packing them into a flat, evenly-divisible
buffer (padding the tail), scattering, and re-slicing each rank's shard.

TPU re-design: the packing math is identical, but the collective is
``lax.psum_scatter`` over a named mesh axis inside shard_map/jit — XLA
already coalesces adjacent collectives it can prove contiguous; this utility
exists for the cases it can't (ragged pytrees) and for API parity. All
shapes are static, so the pack/unpack slicing compiles to free bitcasts.
"""

from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _axis_size(axis: str) -> int:
    """Static size of a bound mesh axis. ``lax.axis_size`` only exists in
    newer JAX; ``psum(1, axis)`` is the portable spelling — a literal psum
    constant-folds to the axis size at trace time, so shapes stay static."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def _flatten_pad(tensors: Sequence[jnp.ndarray], world: int
                 ) -> Tuple[jnp.ndarray, List[Tuple[int, Any, Any]]]:
    """Concat raveled tensors; pad total to a multiple of ``world``.
    Returns (flat, [(numel, shape, dtype), ...])."""
    meta = [(int(t.size), t.shape, t.dtype) for t in tensors]
    flat = jnp.concatenate([t.ravel() for t in tensors])
    total = flat.size
    pad = (-total) % world
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat, meta


def reduce_scatter_coalesced(tensors: Sequence[jnp.ndarray], axis: str
                             ) -> jnp.ndarray:
    """Sum-reduce a list of tensors across ``axis`` and return THIS rank's
    flat shard of the packed buffer (reference coalesced_collectives.py:30).

    Must run inside shard_map/jit with ``axis`` bound. The caller unpacks
    shard-local slices with :func:`shard_layout`.
    """
    world = _axis_size(axis)
    flat, _ = _flatten_pad(tensors, world)
    return lax.psum_scatter(flat, axis, tiled=True)


def all_gather_coalesced(shards: Sequence[jnp.ndarray], axis: str
                         ) -> List[jnp.ndarray]:
    """Reassemble full tensors from per-rank shards in ONE collective
    (reference ZeRO-3 ``all_gather_coalesced``,
    partition_parameters.py:806): each rank holds an equal-size flat shard
    of every tensor; pack -> one tiled all_gather -> reslice.

    ``shards[i]`` is this rank's flat shard; the result's ``out[i]`` is the
    full flat tensor of size ``world * shards[i].size`` (rank-major, the
    partitioning ZeRO-3 uses — the caller reshapes/unpads). Memory is 1x
    the gathered size; the reslice compiles to static slices of the single
    gathered buffer."""
    world = _axis_size(axis)
    sizes = [int(s.size) for s in shards]
    flat = jnp.concatenate([s.ravel() for s in shards])
    per = flat.size
    gathered = lax.all_gather(flat, axis, tiled=True)  # [world * per]
    packs = gathered.reshape(world, per)
    out: List[jnp.ndarray] = []
    offset = 0
    for n, s in zip(sizes, shards):
        # rank-major reassembly: [world, n] -> [world * n]
        out.append(packs[:, offset:offset + n].reshape(world * n)
                   .astype(s.dtype))
        offset += n
    return out


def shard_layout(tensors: Sequence[Any], world: int
                 ) -> List[Tuple[int, int]]:
    """(start, length) of each tensor inside the packed flat buffer —
    callers intersect these with a rank's [rank*shard, (rank+1)*shard)
    window to locate their slice of each tensor (the bookkeeping the
    reference does with partition offsets in stage_1_and_2.py:74)."""
    spans = []
    offset = 0
    for t in tensors:
        n = int(t.size) if hasattr(t, "size") else int(t)
        spans.append((offset, n))
        offset += n
    return spans

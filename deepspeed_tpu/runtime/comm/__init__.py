from deepspeed_tpu.runtime.comm.coalesced_collectives import (  # noqa: F401
    all_gather_coalesced,
    reduce_scatter_coalesced,
)

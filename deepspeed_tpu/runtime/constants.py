"""Config keys and defaults.

Parity with reference ``deepspeed/runtime/constants.py`` (409 LoC of key/default
pairs); only keys meaningful on TPU keep live semantics — GPU-only knobs are
accepted, recorded, and documented as no-ops so reference JSON configs parse
unmodified.
"""

#############################################
# Batch triad (reference runtime/constants.py)
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

#############################################
# Optimizer / scheduler blocks
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM_OPTIMIZER = "fusedadam"
CPU_ADAM_OPTIMIZER = "cpuadam"
CPU_ADAGRAD_OPTIMIZER = "cpuadagrad"
ADAGRAD_OPTIMIZER = "adagrad"
LAMB_OPTIMIZER = "lamb"
FUSED_LAMB_OPTIMIZER = "fusedlamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
SGD_OPTIMIZER = "sgd"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER,
    ADAMW_OPTIMIZER,
    FUSED_ADAM_OPTIMIZER,
    CPU_ADAM_OPTIMIZER,
    CPU_ADAGRAD_OPTIMIZER,
    ADAGRAD_OPTIMIZER,
    LAMB_OPTIMIZER,
    FUSED_LAMB_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER,
    ZERO_ONE_ADAM_OPTIMIZER,
    ONEBIT_LAMB_OPTIMIZER,
    SGD_OPTIMIZER,
]

#############################################
# Precision (fp16 / bf16 / amp)
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0  # 0 => dynamic
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 16
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT = False

BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"
BFLOAT16_ENABLED = "enabled"
BFLOAT16_ENABLED_DEFAULT = False

AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

#############################################
# Misc runtime knobs
#############################################
DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False
DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False
MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False
GRADIENT_NOISE_SCALE = "gradient_noise_scale"

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False

CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]
LOAD_UNIVERSAL_CHECKPOINT = "load_universal"
LOAD_UNIVERSAL_CHECKPOINT_DEFAULT = False
# fault-tolerance knobs (no reference analogue; docs/recovery.md)
CHECKPOINT_KEEP_N = "keep_n"
CHECKPOINT_KEEP_N_DEFAULT = 0  # 0 = keep every tag
CHECKPOINT_VERIFY = "verify"
CHECKPOINT_VERIFY_DEFAULT = True

# Preemption-aware shutdown block (docs/recovery.md): a SIGTERM/SIGINT
# grace handler that saves + commits a final checkpoint before exit.
GRACEFUL_SHUTDOWN = "graceful_shutdown"

# Training health sentinel block (docs/recovery.md "Divergence and hang
# recovery"): anomaly detection + graduated skip/rollback response + hang
# watchdog. The exit codes live here (jax-free module) so the elastic
# agent and worker scripts can share them without importing the runtime.
SENTINEL = "sentinel"
SENTINEL_ENABLED = "enabled"
SENTINEL_ENABLED_DEFAULT = False
# ---------------------------------------------------------------------
# Worker exit-code contract (docs/recovery.md). The elastic agent keys
# its restart policy off these, so every sanctioned abnormal exit in
# sentinel.py / engine.py / health.py must come from HERE — a literal 13
# in one module and a drifted constant in another silently turns a
# terminal divergence into a restart loop (or vice versa).
#
# distinct from any shell/signal convention: "diverged, restarting will
# replay the same failure" vs "crashed, restart is the fix"
DIVERGENCE_EXIT_CODE_DEFAULT = 13
# the hang-watchdog abort code: a hang IS worth restarting (transient
# wedged collective), so it must differ from the divergence code
SENTINEL_HANG_EXIT_CODE_DEFAULT = 14
# the cluster health plane's coordinated world abort: a peer went silent
# mid-step (preempted / wedged host) or an SDC digest cross-check
# mismatched. Every survivor exits with THIS code inside the silence
# budget, so the agent sees one world-level failure (restartable — the
# relaunch resumes from the newest manifest-valid tag) instead of N
# staggered hang timeouts.
PEER_LOSS_EXIT_CODE_DEFAULT = 15
# what each sanctioned code means and whether the agent may restart into
# it (the agent logs this; tests pin the contract)
EXIT_CODE_MEANINGS = {
    DIVERGENCE_EXIT_CODE_DEFAULT:
        ("divergence past the rollback budget", False),
    SENTINEL_HANG_EXIT_CODE_DEFAULT:
        ("hang watchdog abort", True),
    PEER_LOSS_EXIT_CODE_DEFAULT:
        ("cluster health plane: peer loss / SDC coordinated abort", True),
}

# Elastic topology resume (docs/recovery.md "Elastic topology resume"):
# on a restart where the discovered device count changed, the agent
# exports the PREVIOUS world size alongside DS_TPU_NUM_PROCS so the
# worker's load path knows a reshard is expected (runtime/reshard.py
# turns a metadata-less manifest into a clear error instead of a silent
# same-topology assumption). Jax-free home so the agent can import it.
ELASTIC_PREV_WORLD_ENV = "DS_TPU_ELASTIC_PREV_WORLD"

# Telemetry bus + crash-forensics flight recorder block
# (docs/observability.md "Flight recorder"). The dump-dir env var lives
# in telemetry/crash_report.py (jax-free) so supervisors share it.
TELEMETRY = "telemetry"

DATALOADER_DROP_LAST = "dataloader_drop_last"
# True matches what deepspeed_io has always DONE (a hard-coded drop_last
# that ignored this knob); the knob is now honored, and False engages the
# pad-and-mask tail batch so the compiled shape never changes mid-epoch
DATALOADER_DROP_LAST_DEFAULT = True

#############################################
# Pipeline block (reference pipe config)
#############################################
PIPELINE = "pipeline"
PIPELINE_STAGES = "stages"
PIPELINE_PARTITION = "partition"
PIPELINE_SEED_LAYERS = "seed_layers"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL = "activation_checkpoint_interval"

#############################################
# Feature blocks (each has its own config module)
#############################################
ZERO_OPTIMIZATION = "zero_optimization"
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
SPARSE_ATTENTION = "sparse_attention"
CURRICULUM_LEARNING = "curriculum_learning"
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
EIGENVALUE = "eigenvalue"
FLOPS_PROFILER = "flops_profiler"
AUTOTUNING = "autotuning"
ELASTICITY = "elasticity"
COMPRESSION_TRAINING = "compression_training"
MONITOR_TENSORBOARD = "tensorboard"
MONITOR_WANDB = "wandb"
MONITOR_CSV = "csv_monitor"
COMMS_LOGGER = "comms_logger"
STEP_PROFILER = "step_profiler"
# Input data pipeline (deepspeed_tpu/data/, docs/data.md): deterministic
# sharded streaming + sequence packing + background device prefetch
DATA_PIPELINE = "data_pipeline"
AIO = "aio"
NEBULA = "nebula"
QUANTIZE_TRAINING = "quantize_training"
DATA_EFFICIENCY = "data_efficiency"

#############################################
# TPU extension block (new; no reference analogue)
#############################################
TPU = "tpu"
TPU_MESH = "mesh"
TPU_REMAT = "remat"
TPU_DONATE = "donate_params"

# Gradient-allreduce wire format (reference runtime/config.py
# get_communication_data_type + runtime/comm/nccl.py compressed path).
# "int8" routes the data-parallel gradient exchange through the quantized
# collectives in comm/compressed.py (EQuARX-style); fp16/bfp16/fp32 are
# accepted for config parity (XLA reduces in the compute dtype).
COMMUNICATION_DATA_TYPE = "communication_data_type"
COMMUNICATION_DATA_TYPE_DEFAULT = None
COMMUNICATION_DATA_TYPES = ["fp16", "bfp16", "bf16", "fp32", "int8"]

# Routing of reference GPU-only keys we accept but ignore (documented no-ops).
IGNORED_GPU_ONLY_KEYS = [
    "fp16.auto_cast",
    "hybrid_engine",
]

"""Multinode launch backends (reference ``launcher/multinode_runner.py``:
``PDSHRunner`` :45, ``OpenMPIRunner`` :109, ``SlurmRunner`` :164,
``MVAPICHRunner`` :211).

Each runner turns (active hosts, per-host command) into ONE external launch
command for the corresponding cluster tool. The TPU re-design keeps the
reference's split — the runner only *builds* command lines (testable without
the tools installed); ``runner.main`` executes them — but the per-host
payload is the one-process-per-host JAX rendezvous command from
``runner.build_host_command``, not a per-GPU fan-out.

``GcloudTPURunner`` is the TPU-native addition: ``gcloud compute tpus
tpu-vm ssh --worker=all`` drives every worker of a pod slice with one
command, which is how multi-host TPU jobs actually launch on GCE.
"""

import os
import shlex
from typing import Dict, List, Tuple

__all__ = ["PDSHRunner", "OpenMPIRunner", "SlurmRunner", "GcloudTPURunner",
           "get_runner"]


def _shjoin(cmd: List[str]) -> str:
    return " ".join(shlex.quote(c) for c in cmd)


class MultiNodeRunner:
    """Base: build one launch command for all hosts."""

    name = "base"

    def __init__(self, exports: Dict[str, str] = None):
        # env forwarded to every host (reference exports NCCL_*/PYTHON*;
        # here the JAX/libtpu knobs matter)
        self.exports = dict(exports or {})

    def backend_exists(self) -> bool:
        raise NotImplementedError

    def get_cmd(self, hosts: List[str], per_host_cmds: List[List[str]],
                hostfile: str) -> List[str]:
        """hosts[i] runs per_host_cmds[i]."""
        raise NotImplementedError

    def _export_prefix(self) -> str:
        return "".join(f"export {k}={shlex.quote(v)}; "
                       for k, v in sorted(self.exports.items()))

    def _remote_prefix(self) -> str:
        """cd to the launch cwd + propagate PYTHONPATH, matching the
        builtin ssh backend (runner.build_ssh_command) so relative script
        paths resolve identically under every launcher."""
        prefix = f"cd {shlex.quote(os.getcwd())} && "
        pythonpath = os.environ.get("PYTHONPATH", "")
        if pythonpath:
            prefix += f"export PYTHONPATH={shlex.quote(pythonpath)} && "
        return prefix + self._export_prefix()


def _strip_env_prefix(cmd: List[str]) -> Tuple[Dict[str, str], List[str]]:
    """Split runner.build_host_command's ``env K=V ... prog args`` prefix
    into ({K: V}, [prog, args...]); mpirun/srun exec argv directly (no
    shell), so assignments must travel via -x/--export instead."""
    env: Dict[str, str] = {}
    rest = list(cmd)
    if rest and rest[0] == "env":
        rest = rest[1:]
        while rest and "=" in rest[0] and not os.sep in rest[0].split("=")[0]:
            k, v = rest.pop(0).split("=", 1)
            env[k] = v
    return env, rest


class PDSHRunner(MultiNodeRunner):
    """Parallel-ssh fan-out (reference PDSHRunner :45). pdsh runs ONE
    command on every host; each host picks its payload by matching any of
    its identities (short/FQDN hostname or IPs) against the hostfile
    names — substring case-matching so FQDN-vs-short and IP hostfiles all
    resolve."""

    name = "pdsh"

    def backend_exists(self) -> bool:
        from shutil import which

        return which("pdsh") is not None

    def get_cmd(self, hosts, per_host_cmds, hostfile):
        cases = []
        for host, cmd in zip(hosts, per_host_cmds):
            # arm matches the hostfile name as a word inside the host's
            # identity string (short + fqdn + IPs)
            cases.append(
                f"*\" {host} \"*) {self._remote_prefix()}{_shjoin(cmd)} ;;")
        ident = ('" $(hostname -s) $(hostname -f 2>/dev/null) '
                 '$(hostname -I 2>/dev/null) "')
        script = (f"case {ident} in {' '.join(cases)} "
                  f"*) echo unmatched host >&2; exit 3 ;; esac")
        return ["pdsh", "-S", "-f", str(len(hosts)), "-w",
                ",".join(hosts), script]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun-based launch (reference OpenMPIRunner :109): one rank per
    host; the payload reads OMPI_COMM_WORLD_RANK as its process id."""

    name = "openmpi"

    def backend_exists(self) -> bool:
        from shutil import which

        return which("mpirun") is not None

    def get_cmd(self, hosts, per_host_cmds, hostfile):
        env, payload = _strip_env_prefix(per_host_cmds[0])
        env.pop("DS_TPU_PROC_ID", None)  # rank comes from OMPI_* env
        env.update(self.exports)
        cmd = ["mpirun", "-n", str(len(hosts)), "--host", ",".join(hosts),
               "--map-by", "ppr:1:node"]
        for k, v in sorted(env.items()):
            cmd += ["-x", f"{k}={v}"]
        return cmd + payload


class SlurmRunner(MultiNodeRunner):
    """srun-based launch (reference SlurmRunner :164): one task per node;
    the payload reads SLURM_PROCID as its process id."""

    name = "slurm"

    def backend_exists(self) -> bool:
        from shutil import which

        return which("srun") is not None

    def get_cmd(self, hosts, per_host_cmds, hostfile):
        env, payload = _strip_env_prefix(per_host_cmds[0])
        env.pop("DS_TPU_PROC_ID", None)  # rank comes from SLURM_PROCID
        env.update(self.exports)
        cmd = ["srun", "--nodes", str(len(hosts)),
               "--ntasks-per-node", "1",
               "--nodelist", ",".join(hosts),
               "--export", "ALL" + "".join(
                   f",{k}={v}" for k, v in sorted(env.items()))]
        return cmd + payload


class GcloudTPURunner(MultiNodeRunner):
    """``gcloud compute tpus tpu-vm ssh --worker=all`` (the native launch
    path for TPU pod slices; hosts list is ignored — the slice topology is
    the worker set)."""

    name = "gcloud"

    def __init__(self, tpu_name: str = None, zone: str = None, **kw):
        super().__init__(**kw)
        self.tpu_name = tpu_name or os.environ.get("DS_TPU_NAME", "")
        self.zone = zone or os.environ.get("DS_TPU_ZONE", "")

    def backend_exists(self) -> bool:
        from shutil import which

        return which("gcloud") is not None and bool(self.tpu_name)

    def get_cmd(self, hosts, per_host_cmds, hostfile):
        # every worker runs the same payload; per-worker identity comes
        # from the TPU runtime metadata jax.distributed reads natively, so
        # the DS_TPU_* rendezvous envs are dropped entirely
        # no cd-to-launch-cwd here: TPU VMs share no filesystem with the
        # launch workstation — code is staged in the VM home and the
        # command runs from there
        _env, payload = _strip_env_prefix(per_host_cmds[0])
        remote = self._export_prefix() + _shjoin(payload)
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", self.tpu_name,
               "--worker=all", f"--command={remote}"]
        if self.zone:
            cmd.insert(6, f"--zone={self.zone}")
        return cmd


_RUNNERS = {r.name: r for r in
            (PDSHRunner, OpenMPIRunner, SlurmRunner, GcloudTPURunner)}


def get_runner(name: str, **kw) -> MultiNodeRunner:
    if name not in _RUNNERS:
        raise ValueError(
            f"unknown launcher {name!r}; available: {sorted(_RUNNERS)}")
    return _RUNNERS[name](**kw)

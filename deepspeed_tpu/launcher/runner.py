"""Multi-host launcher (reference ``launcher/runner.py:353`` + ``bin/deepspeed``).

The reference spawns one process per GPU per node over PDSH/MPI/Slurm and
rendezvouses through torch.distributed. The TPU topology is different —
ONE process per host, all local chips owned by that process, rendezvous via
``jax.distributed.initialize(coordinator, num_processes, process_id)`` —
so the runner's job is: parse a hostfile (same MPI-ish ``host slots=N``
format), apply --include/--exclude filters, pick a coordinator, and launch
the user script on every host over ssh (or locally for single-host) with
the JAX cluster env set.

Env protocol (consumed by deepspeed_tpu.comm.init_distributed):
  DS_TPU_COORDINATOR  host:port of process 0
  DS_TPU_NUM_PROCS    number of host processes
  DS_TPU_PROC_ID      this host's index
"""

import argparse
import base64
import json
import os
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.procgroup import (reap_process_group,
                                           spawn_process_group)

DEFAULT_MASTER_PORT = 29500


def fetch_hostfile(hostfile_path: str) -> "OrderedDict[str, int]":
    """Parse MPI-style ``hostname slots=N`` lines (reference runner.py:177).
    Returns an ordered {hostname: slot_count} map."""
    if not os.path.isfile(hostfile_path):
        raise FileNotFoundError(f"hostfile {hostfile_path} not found")
    resources: "OrderedDict[str, int]" = OrderedDict()
    with open(hostfile_path) as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            try:
                hostname, slots = line.split()
                _, count = slots.split("=")
                count = int(count)
            except ValueError as e:
                raise ValueError(
                    f"bad hostfile line {lineno}: {raw!r} "
                    f"(want 'host slots=N')") from e
            if hostname in resources:
                raise ValueError(f"duplicate host {hostname} in hostfile")
            resources[hostname] = count
    if not resources:
        raise ValueError(f"hostfile {hostfile_path} is empty")
    return resources


def _parse_filter(spec: str) -> Dict[str, Optional[List[int]]]:
    """'worker-0@worker-1:0,2' -> {worker-0: None, worker-1: [0, 2]}"""
    out: Dict[str, Optional[List[int]]] = {}
    if not spec:
        return out
    for part in spec.split("@"):
        if ":" in part:
            host, slots = part.split(":")
            out[host] = sorted(int(s) for s in slots.split(","))
        else:
            out[part] = None
    return out


def parse_resource_filter(host_info: "OrderedDict[str, int]",
                          include_str: str = "",
                          exclude_str: str = "") \
        -> "OrderedDict[str, List[int]]":
    """Apply --include/--exclude (reference runner.py:218). Mutually
    exclusive. Returns {host: [slot ids]}."""
    if include_str and exclude_str:
        raise ValueError("--include and --exclude are mutually exclusive")
    expanded = OrderedDict(
        (h, list(range(n))) for h, n in host_info.items())
    if include_str:
        inc = _parse_filter(include_str)
        filtered = OrderedDict()
        for host, slots in inc.items():
            if host not in expanded:
                raise ValueError(f"included host {host} not in hostfile")
            use = slots if slots is not None else expanded[host]
            bad = set(use) - set(expanded[host])
            if bad:
                raise ValueError(f"host {host} has no slots {sorted(bad)}")
            filtered[host] = use
        return filtered
    if exclude_str:
        exc = _parse_filter(exclude_str)
        filtered = OrderedDict()
        for host, slots in expanded.items():
            if host in exc:
                if exc[host] is None:
                    continue
                keep = [s for s in slots if s not in exc[host]]
                if keep:
                    filtered[host] = keep
            else:
                filtered[host] = slots
        if not filtered:
            raise ValueError("exclusion filter removed every host")
        return filtered
    return expanded


def encode_world_info(active: "OrderedDict[str, List[int]]") -> str:
    """base64 world map, passed to per-host launchers (reference
    runner.py world_info scheme)."""
    return base64.urlsafe_b64encode(
        json.dumps(active).encode()).decode()


def decode_world_info(blob: str) -> Dict[str, List[int]]:
    return json.loads(base64.urlsafe_b64decode(blob.encode()).decode())


def build_host_command(args, host_idx: int, num_hosts: int,
                       coordinator: str, world_info: str) -> List[str]:
    """Command line run on one host."""
    env_prefix = [
        "env",
        f"DS_TPU_COORDINATOR={coordinator}",
        f"DS_TPU_NUM_PROCS={num_hosts}",
        f"DS_TPU_PROC_ID={host_idx}",
        f"DS_TPU_WORLD_INFO={world_info}",
    ]
    cmd = env_prefix + [sys.executable, "-u", args.user_script]
    cmd += args.user_args
    return cmd


def build_ssh_command(host: str, inner_cmd: List[str],
                      ssh_port: Optional[int] = None,
                      cwd: Optional[str] = None) -> List[str]:
    """Remote command runs from the launch cwd with the launch PYTHONPATH,
    so repo-relative script/data paths resolve the same on every host
    (reference runner prefixes 'cd {os.path.abspath('.')}')."""
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    remote = f"cd {shlex.quote(cwd or os.getcwd())} && "
    pythonpath = os.environ.get("PYTHONPATH", "")
    if pythonpath:
        remote += f"export PYTHONPATH={shlex.quote(pythonpath)} && "
    remote += " ".join(shlex.quote(c) for c in inner_cmd)
    ssh += [host, remote]
    return ssh


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="deepspeed_tpu multi-host launcher")
    p.add_argument("-H", "--hostfile", default="/job/hostfile",
                   help="MPI-style hostfile: one 'host slots=N' per line")
    p.add_argument("-i", "--include", default="",
                   help="e.g. 'worker-0@worker-1:0,2'")
    p.add_argument("-e", "--exclude", default="",
                   help="inverse of --include")
    p.add_argument("--num_nodes", type=int, default=-1)
    p.add_argument("--master_port", type=int, default=DEFAULT_MASTER_PORT)
    p.add_argument("--master_addr", default="",
                   help="coordinator address; default = first active host")
    p.add_argument("--ssh_port", type=int, default=None)
    p.add_argument("--launcher", default="ssh",
                   choices=["ssh", "pdsh", "openmpi", "slurm", "gcloud"],
                   help="multinode backend (reference multinode_runner.py); "
                        "'ssh' = builtin per-host ssh fan-out")
    p.add_argument("--force_multi", action="store_true")
    p.add_argument("--dry_run", action="store_true",
                   help="print the per-host commands without launching")
    p.add_argument("--autotuning", default="", choices=["run", "tune"],
                   help="tune: relaunch the script per experiment and rank "
                        "configs; run: then launch with the best one "
                        "(reference launcher --autotuning)")
    p.add_argument("user_script")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _user_script_index(raw, user_script, user_args) -> int:
    """Index of the user script in the original runner argv.

    ``user_args`` is ``nargs=REMAINDER``, so the script sits exactly at
    ``len(raw) - len(user_args) - 1``. A plain ``raw.index(user_script)``
    (first occurrence) truncates the runner's own options when one of
    their VALUES equals the script path (e.g. ``--include train.py``
    typo'd before the real ``train.py``); a last-occurrence search fails
    the mirror case where the script name recurs inside ``user_args``.
    The arithmetic split is exact for both; the rindex fallback only
    covers argv lists that didn't come from ``parse_args`` verbatim.
    """
    at = len(raw) - len(user_args) - 1
    if 0 <= at < len(raw) and raw[at] == user_script:
        return at
    return len(raw) - 1 - raw[::-1].index(user_script)


def main(argv=None) -> int:
    args = parse_args(argv)

    if args.autotuning:
        if args.force_multi or args.dry_run:
            # these flags shape the FINAL launch topology, which the tuner
            # re-derives per experiment; quietly dropping them would tune
            # (and launch!) on the wrong topology
            raise SystemExit(
                "--autotuning does not compose with --force_multi/"
                "--dry_run; give the tuner a hostfile instead (it "
                "schedules experiments across those hosts in parallel)")
        from deepspeed_tpu.autotuning.cli import (
            _find_config,
            _swapped_args,
            run_autotuning,
        )

        hosts = None
        final_launch = None
        if os.path.isfile(args.hostfile):
            # parallel experiment scheduling over the host pool
            # (reference ResourceManager, autotuning/scheduler.py:27)
            hosts = fetch_hostfile(args.hostfile)
            hosts = parse_resource_filter(hosts, args.include,
                                          args.exclude)

            def final_launch(best_cfg, _argv=argv):
                # mode `run` finalizer: relaunch through THIS runner with
                # the winning config and the original multi-host options,
                # so the production job runs on the tuned topology
                raw = list(_argv) if _argv is not None else sys.argv[1:]
                # strip --autotuning in every argparse spelling (exact,
                # '=value', prefix abbreviation) — but only among the
                # RUNNER's options, i.e. tokens before the user script
                script_at = _user_script_index(raw, args.user_script,
                                               args.user_args)
                kept, skip = [], False
                for j, tok in enumerate(raw[:script_at]):
                    if skip:
                        skip = False
                        continue
                    base = tok.split("=", 1)[0]
                    if (base.startswith("--a") and len(base) >= 3
                            and "--autotuning".startswith(base)):
                        skip = "=" not in tok
                        continue
                    kept.append(tok)
                raw = kept + raw[script_at:]
                ci, _ = _find_config(raw)
                return main(_swapped_args(raw, ci, best_cfg))

        return run_autotuning(args.autotuning, args.user_script,
                              list(args.user_args), hosts=hosts,
                              final_launch=final_launch)

    multi_host = os.path.isfile(args.hostfile) or args.force_multi
    if multi_host:
        resources = fetch_hostfile(args.hostfile)
        active = parse_resource_filter(resources, args.include,
                                       args.exclude)
        if args.num_nodes > 0:
            active = OrderedDict(list(active.items())[:args.num_nodes])
    else:
        active = OrderedDict([("localhost", [0])])

    hosts = list(active.keys())
    coordinator = (args.master_addr or hosts[0]) + f":{args.master_port}"
    world_info = encode_world_info(active)
    logger.info(f"launching on {len(hosts)} host(s); "
                f"coordinator {coordinator}")

    per_host = [build_host_command(args, idx, len(hosts), coordinator,
                                   world_info)
                for idx in range(len(hosts))]

    if args.launcher != "ssh":
        from deepspeed_tpu.launcher.multinode_runner import get_runner

        runner = get_runner(args.launcher)
        if not args.dry_run and not runner.backend_exists():
            raise RuntimeError(
                f"launcher backend {args.launcher!r} unavailable "
                f"(tool not installed, or DS_TPU_NAME unset for gcloud)")
        cmd = runner.get_cmd(hosts, per_host, args.hostfile)
        if args.dry_run:
            print(" ".join(shlex.quote(c) for c in cmd))
            return 0
        return subprocess.call(cmd)

    procs = []
    for idx, host in enumerate(hosts):
        inner = per_host[idx]
        cmd = (inner if host in ("localhost", "127.0.0.1")
               else build_ssh_command(host, inner, args.ssh_port))
        if args.dry_run:
            print(" ".join(shlex.quote(c) for c in cmd))
            continue
        # own process group per worker: interrupting the launcher must reap
        # the worker's whole tree (a JAX child masking/outliving TERM was
        # the 21-hour leak of ROADMAP item 1), not just the direct child
        procs.append(spawn_process_group(cmd))
    if args.dry_run:
        return 0

    rc = 0
    try:
        for p in procs:
            p.wait()
            rc = rc or p.returncode
    except KeyboardInterrupt:
        for p in procs:
            reap_process_group(p)
        rc = 1
    if rc != 0:
        # sweep per-rank flight-recorder dumps into one crash report
        # (workers inherit DS_TPU_TELEMETRY_DIR from this process' env);
        # best-effort — forensics must not change the exit code
        from deepspeed_tpu.telemetry.crash_report import (
            TELEMETRY_DIR_ENV,
            sweep_blackbox_dumps,
        )

        tdir = os.environ.get(TELEMETRY_DIR_ENV)
        if tdir:
            try:
                report = sweep_blackbox_dumps(tdir)
            except Exception as e:
                logger.warning(f"blackbox sweep failed: {e}")
                report = None
            if report is not None:
                logger.error(
                    f"crash report: {report['path']} — "
                    f"{report['num_ranks']} rank(s), "
                    f"reasons={report['reasons']}")
    return rc


if __name__ == "__main__":
    sys.exit(main())

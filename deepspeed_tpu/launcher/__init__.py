from deepspeed_tpu.launcher.runner import (  # noqa: F401
    build_host_command,
    build_ssh_command,
    decode_world_info,
    encode_world_info,
    fetch_hostfile,
    main,
    parse_resource_filter,
)

"""Nebula checkpoint-service glue (reference ``deepspeed/nebula/`` is
config/constants only — the service itself is Azure-managed). Parsed for
config compatibility; enabling it routes checkpoints through the async
tiered pattern of runtime/checkpoint_engine."""

from dataclasses import dataclass


@dataclass
class NebulaConfig:
    enabled: bool = False
    persistent_storage_path: str = ""
    persistent_time_interval: int = 100
    num_of_version_in_retention: int = 2
    enable_nebula_load: bool = True

    @classmethod
    def from_dict(cls, d):
        d = d or {}
        return cls(**{k: v for k, v in d.items()
                      if k in cls.__dataclass_fields__})

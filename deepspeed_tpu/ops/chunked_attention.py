"""Chunked (online-softmax) attention — the XLA-native long-context path.

Reference counterpart: the sparse/flash attention kernels exist to avoid
materializing the [T, T] score matrix (ops/sparse_attention,
triton_flash_attn). The Pallas flash kernel here covers seq <= 8192 on
the current toolchain (its per-head [T, d] VMEM working set hits the
16 MB scoped-vmem ceiling at 16k). This module removes the length
ceiling with plain XLA: a ``lax.scan`` over KV chunks carrying the
online-softmax state (running max m, normalizer l, weighted accumulator
acc — the Rabe-Staats / flash-attention recurrence), so peak memory is
O(T * chunk) scores per step instead of O(T^2), and ``jax.checkpoint``
on the scan body makes the backward recompute each chunk's scores
instead of saving them (32 chunks x [H, T, chunk] would otherwise be
saved for the vjp).

Accuracy: softmax statistics accumulate in f32 regardless of the
compute dtype; the result matches the einsum reference to bf16/f16
rounding.

Design note (measured): a q-blocked variant that lax.cond-skips the
fully-masked KV chunks of causal runs (halving attention FLOPs) was
tried and REGRESSED at 32k — 15.9 vs 13.1 s/step — because the double
scan turns 32 large well-pipelined iterations into 1024 small ones and
the toolchain's attention-dot throughput (~13 TF at d=64) leaves the
saved FLOPs cheaper than the added loop overhead. Revisit if Mosaic
reaches normal speed (a fused chunk kernel changes the trade).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def chunked_attention(q, k, v, *, causal: bool = True, chunk: int = 1024):
    """Attention over ``[B, T, H, D]`` tensors with bounded score memory.

    ``T`` must be divisible by ``chunk`` (pad the sequence; the callers
    gate on this the same way the flash path gates on 128-alignment).
    Returns ``[B, T, H, D]`` in ``q``'s dtype.
    """
    B, T, H, D = q.shape
    if T % chunk:
        raise ValueError(f"seq len {T} not divisible by chunk {chunk}")
    n_chunks = T // chunk
    scale = 1.0 / np.sqrt(D)
    dtype = q.dtype

    # [B, H, T, D] layout keeps the per-chunk contraction MXU-friendly
    qh = q.transpose(0, 2, 1, 3).astype(dtype)
    kh = k.transpose(0, 2, 1, 3).astype(dtype)
    vh = v.transpose(0, 2, 1, 3).astype(dtype)
    q_pos = jnp.arange(T)

    neg = jnp.float32(jnp.finfo(jnp.float32).min)

    def body(carry, idx):
        m, l, acc = carry  # [B,H,T], [B,H,T], [B,H,T,D] — all f32
        start = idx * chunk
        k_c = lax.dynamic_slice_in_dim(kh, start, chunk, axis=2)
        v_c = lax.dynamic_slice_in_dim(vh, start, chunk, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, k_c,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = start + jnp.arange(chunk)
            s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, neg)
        m_c = jnp.max(s, axis=-1)                      # [B,H,T]
        m_new = jnp.maximum(m, m_c)
        # exp(neg - m_new) underflows to exactly 0, so fully-masked rows
        # contribute nothing and l stays 0 until a visible chunk arrives
        p = jnp.exp(s - m_new[..., None])              # [B,H,T,chunk]
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(dtype), v_c,
            preferred_element_type=jnp.float32)
        return (m_new, l_new, acc_new), None

    init = (
        jnp.full((B, H, T), neg, jnp.float32),
        jnp.zeros((B, H, T), jnp.float32),
        jnp.zeros((B, H, T, D), jnp.float32),
    )
    (m, l, acc), _ = lax.scan(
        jax.checkpoint(body), init, jnp.arange(n_chunks))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(dtype).transpose(0, 2, 1, 3)

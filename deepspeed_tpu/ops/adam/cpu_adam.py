"""Host-offloaded CPU Adam/Adagrad (reference ``ops/adam/cpu_adam.py:12``
DeepSpeedCPUAdam / ``ops/adagrad/cpu_adagrad.py:10``).

Runs the optimizer math on host cores over numpy views of the optimizer
shard while the device keeps only bf16/fp32 params — the ZeRO-Offload
pattern. The C++ kernel (ops/native/csrc/cpu_adam.cpp) is multithreaded and
auto-vectorized; a pure-numpy fallback keeps the API working where the
native library cannot build.
"""

import ctypes
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger


def _f32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _as_f32_flat(a: np.ndarray) -> np.ndarray:
    out = np.ascontiguousarray(a, dtype=np.float32).reshape(-1)
    return out


class DeepSpeedCPUAdam:
    """Fused host Adam over flat numpy shards.

    ``step(params_list, grads_list)`` updates params in place (each entry a
    float32 numpy array; views into pinned buffers work too).
    """

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 adamw_mode: bool = True, fp32_optimizer_states: bool = True):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.step_count = 0
        self._m: Dict[int, np.ndarray] = {}
        self._v: Dict[int, np.ndarray] = {}
        self._lib = None
        try:
            from deepspeed_tpu.ops.native.builder import load_library

            self._lib = load_library()
        except Exception as e:  # pragma: no cover - build env dependent
            logger.warning(f"native cpu_adam unavailable ({e}); "
                           f"using numpy fallback")

    def _state_for(self, i: int, n: int):
        if i not in self._m:
            self._m[i] = np.zeros(n, dtype=np.float32)
            self._v[i] = np.zeros(n, dtype=np.float32)
        if self._m[i].size != n:
            raise ValueError(
                f"param {i} changed size ({self._m[i].size} -> {n}); the "
                f"param list must be stable across steps")
        return self._m[i], self._v[i]

    def update_tensor(self, p: np.ndarray, g: np.ndarray, m: np.ndarray,
                      v: np.ndarray) -> None:
        """Fused Adam update of ONE tensor against caller-owned moment
        buffers (the pipelined-swap path brings m/v in from disk per
        sub-group; swapper.py PipelinedOptimizerSwapper). Uses the current
        ``step_count`` — the caller advances it once per step."""
        if p.dtype != np.float32 or not p.flags.c_contiguous:
            raise TypeError(
                f"param must be contiguous float32 (got {p.dtype}); "
                f"keep master weights fp32 on host")
        flat_p = p.reshape(-1)
        flat_g = _as_f32_flat(g)
        if self._lib is not None:
            beta1, beta2 = self.betas
            self._lib.ds_adam_update(
                _f32ptr(flat_p), _f32ptr(flat_g), _f32ptr(m), _f32ptr(v),
                flat_p.size, self.step_count, self.lr, beta1, beta2,
                self.eps, self.weight_decay,
                1 if self.adamw_mode else 0)
        else:
            self._numpy_adam(flat_p, flat_g, m, v)

    def step(self, params: List[np.ndarray],
             grads: List[np.ndarray]) -> int:
        """One fused Adam step over every (param, grad) pair."""
        self.step_count += 1
        for i, (p, g) in enumerate(zip(params, grads)):
            m, v = self._state_for(i, p.size)
            self.update_tensor(p, g, m, v)
        return self.step_count

    def _numpy_adam(self, p, g, m, v):
        beta1, beta2 = self.betas
        t = self.step_count
        if not self.adamw_mode and self.weight_decay > 0:
            g = g + self.weight_decay * p
        m *= beta1
        m += (1 - beta1) * g
        v *= beta2
        v += (1 - beta2) * g * g
        bias1 = 1 - beta1 ** t
        bias2 = 1 - beta2 ** t
        denom = np.sqrt(v / bias2) + self.eps
        if self.adamw_mode and self.weight_decay > 0:
            p *= 1 - self.lr * self.weight_decay
        p -= self.lr / bias1 * (m / denom)

    # reference also exposes per-group state_dict-ish access
    def state(self, i: int):
        return {"exp_avg": self._m.get(i), "exp_avg_sq": self._v.get(i)}


class DeepSpeedCPUAdagrad:
    """Fused host Adagrad (reference DeepSpeedCPUAdagrad)."""

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.step_count = 0
        self._sq: Dict[int, np.ndarray] = {}
        self._lib = None
        try:
            from deepspeed_tpu.ops.native.builder import load_library

            self._lib = load_library()
        except Exception:  # pragma: no cover
            pass

    def step(self, params: List[np.ndarray],
             grads: List[np.ndarray]) -> int:
        self.step_count += 1
        for i, (p, g) in enumerate(zip(params, grads)):
            if p.dtype != np.float32 or not p.flags.c_contiguous:
                raise TypeError(
                    f"param {i} must be contiguous float32 (got {p.dtype})")
            flat_p = p.reshape(-1)
            flat_g = _as_f32_flat(g)
            if i not in self._sq:
                self._sq[i] = np.zeros(flat_p.size, dtype=np.float32)
            elif self._sq[i].size != flat_p.size:
                raise ValueError(
                    f"param {i} changed size; param list must be stable")
            sq = self._sq[i]
            if self._lib is not None:
                self._lib.ds_adagrad_update(
                    _f32ptr(flat_p), _f32ptr(flat_g), _f32ptr(sq),
                    flat_p.size, self.step_count, self.lr, self.eps,
                    self.weight_decay)
            else:
                if self.weight_decay > 0:
                    flat_g = flat_g + self.weight_decay * flat_p
                sq += flat_g * flat_g
                flat_p -= self.lr * flat_g / (np.sqrt(sq) + self.eps)
        return self.step_count

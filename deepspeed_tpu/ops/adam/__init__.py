from deepspeed_tpu.ops.adam.cpu_adam import (  # noqa: F401
    DeepSpeedCPUAdagrad,
    DeepSpeedCPUAdam,
)

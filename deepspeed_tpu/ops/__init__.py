"""Op library (reference ``deepspeed/ops/`` + ``csrc/``, SURVEY.md §2.4).

Compute-path kernels are Pallas (ops/pallas); elementwise/grouped ops that
XLA already fuses optimally are pure jnp with the reference's API surface.
"""

from deepspeed_tpu.ops.quantizer import (  # noqa: F401
    dequantize,
    fake_quantize,
    int8_matmul,
    quantize,
    quantize_weight_per_column,
)
from deepspeed_tpu.ops.rotary import apply_rotary_pos_emb, rotary_angles  # noqa: F401


def __getattr__(name):
    # pallas kernels imported lazily (pallas import is heavier)
    if name in ("flash_attention", "fused_adamw", "fused_adamw_update"):
        from deepspeed_tpu.ops import pallas as _p

        return getattr(_p, name)
    raise AttributeError(name)

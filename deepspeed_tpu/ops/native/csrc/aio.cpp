// Async file I/O threadpool (TPU-native equivalent of reference csrc/aio:
// deepspeed_aio_common + py_ds_aio bindings over libaio).
//
// Role: overlap parameter/optimizer-state swaps to local SSD with compute
// (ZeRO-Infinity's NVMe tier). Implemented as a portable pread/pwrite
// threadpool rather than libaio: TPU-VM local SSDs saturate well below a
// few worker threads, and the handle API (submit/wait) matches the
// reference's aio_handle semantics.
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <fcntl.h>
#include <functional>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

class ThreadPool {
 public:
  explicit ThreadPool(int workers) : stop_(false), pending_(0) {
    for (int i = 0; i < workers; ++i) {
      threads_.emplace_back([this] { run(); });
    }
  }
  ~ThreadPool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : threads_) t.join();
  }
  void submit(std::function<void()> fn) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      jobs_.push(std::move(fn));
      ++pending_;
    }
    cv_.notify_one();
  }
  void wait_all() {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this] { return pending_ == 0; });
  }

 private:
  void run() {
    for (;;) {
      std::function<void()> job;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !jobs_.empty(); });
        if (stop_ && jobs_.empty()) return;
        job = std::move(jobs_.front());
        jobs_.pop();
      }
      job();
      {
        std::unique_lock<std::mutex> lk(mu_);
        if (--pending_ == 0) done_cv_.notify_all();
      }
    }
  }
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  std::queue<std::function<void()>> jobs_;
  std::vector<std::thread> threads_;
  bool stop_;
  int pending_;
};

struct AioHandle {
  ThreadPool pool;
  std::mutex err_mu;
  int error = 0;
  explicit AioHandle(int workers) : pool(workers) {}
  void set_error(int e) {
    std::unique_lock<std::mutex> lk(err_mu);
    if (!error) error = e;
  }
};

bool full_pread(int fd, char* buf, int64_t count, int64_t offset) {
  while (count > 0) {
    ssize_t got = pread(fd, buf, (size_t)count, (off_t)offset);
    if (got < 0 && errno == EINTR) continue;  // signal-interrupted: retry
    if (got <= 0) return false;               // error or premature EOF
    buf += got;
    count -= got;
    offset += got;
  }
  return true;
}

bool full_pwrite(int fd, const char* buf, int64_t count, int64_t offset) {
  while (count > 0) {
    ssize_t put = pwrite(fd, buf, (size_t)count, (off_t)offset);
    if (put < 0 && errno == EINTR) continue;
    if (put <= 0) return false;
    buf += put;
    count -= put;
    offset += put;
  }
  return true;
}

}  // namespace

extern "C" {

void* ds_aio_handle_create(int num_threads) {
  return new AioHandle(num_threads > 0 ? num_threads : 1);
}

void ds_aio_handle_destroy(void* h) { delete (AioHandle*)h; }

// Async read of `count` bytes at `offset` from `path` into `buffer`.
void ds_aio_pread(void* h, const char* path, char* buffer, int64_t count,
                  int64_t offset) {
  auto* handle = (AioHandle*)h;
  std::string p(path);
  handle->pool.submit([handle, p, buffer, count, offset] {
    int fd = open(p.c_str(), O_RDONLY);
    if (fd < 0) {
      handle->set_error(1);
      return;
    }
    if (!full_pread(fd, buffer, count, offset)) handle->set_error(2);
    close(fd);
  });
}

// Async write; creates/extends the file as needed.
void ds_aio_pwrite(void* h, const char* path, const char* buffer,
                   int64_t count, int64_t offset) {
  auto* handle = (AioHandle*)h;
  std::string p(path);
  handle->pool.submit([handle, p, buffer, count, offset] {
    int fd = open(p.c_str(), O_WRONLY | O_CREAT, 0644);
    if (fd < 0) {
      handle->set_error(3);
      return;
    }
    if (!full_pwrite(fd, buffer, count, offset)) handle->set_error(4);
    close(fd);
  });
}

// Block until every submitted op completes; returns 0 on success, else the
// first error code.
int ds_aio_wait(void* h) {
  auto* handle = (AioHandle*)h;
  handle->pool.wait_all();
  std::unique_lock<std::mutex> lk(handle->err_mu);
  int e = handle->error;
  handle->error = 0;
  return e;
}

}  // extern "C"

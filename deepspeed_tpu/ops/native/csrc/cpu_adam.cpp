// Host-offloaded fused Adam/AdamW (TPU-native equivalent of reference
// csrc/adam/cpu_adam.cpp:286-291 create_adam/adam_update).
//
// The reference hand-writes AVX256/512 intrinsics (csrc/includes/simd.h);
// here the inner loops are written to auto-vectorize under -O3 -march=native
// and parallelize across a std::thread pool — same role: run the optimizer
// math on host cores while device memory holds only params, for
// ZeRO-Offload-style training.
#include <atomic>
#include <functional>
#include <cmath>
#include <cstddef>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

struct AdamState {
  float beta1;
  float beta2;
  float eps;
  float weight_decay;
  bool adamw_mode;
};

void adam_span(float* p, const float* g, float* m, float* v, size_t n,
               float lr, float beta1, float beta2, float eps,
               float weight_decay, float bias1, float bias2,
               bool adamw_mode) {
  const float step_size = -lr / bias1;
  const float w_decay = adamw_mode ? 1.0f - lr * weight_decay : 0.0f;
  for (size_t i = 0; i < n; ++i) {
    float grad = g[i];
    if (!adamw_mode && weight_decay > 0.0f) grad += weight_decay * p[i];
    m[i] = beta1 * m[i] + (1.0f - beta1) * grad;
    v[i] = beta2 * v[i] + (1.0f - beta2) * grad * grad;
    float denom = std::sqrt(v[i] / bias2) + eps;
    float update = m[i] / denom;
    if (adamw_mode && weight_decay > 0.0f) p[i] *= w_decay;
    p[i] += step_size * update;
  }
}

void parallel_for(size_t n, size_t min_chunk,
                  const std::function<void(size_t, size_t)>& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  size_t workers = hw ? hw : 1;
  size_t chunk = (n + workers - 1) / workers;
  if (chunk < min_chunk) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> threads;
  for (size_t start = 0; start < n; start += chunk) {
    size_t end = start + chunk < n ? start + chunk : n;
    threads.emplace_back(fn, start, end);
  }
  for (auto& t : threads) t.join();
}

}  // namespace

extern "C" {

// One fused Adam step over a flat parameter shard.
void ds_adam_update(float* params, const float* grads, float* exp_avg,
                    float* exp_avg_sq, int64_t n, int step, float lr,
                    float beta1, float beta2, float eps, float weight_decay,
                    int adamw_mode) {
  const float bias1 = 1.0f - std::pow(beta1, (float)step);
  const float bias2 = 1.0f - std::pow(beta2, (float)step);
  parallel_for((size_t)n, 1 << 16, [&](size_t s, size_t e) {
    adam_span(params + s, grads + s, exp_avg + s, exp_avg_sq + s, e - s, lr,
              beta1, beta2, eps, weight_decay, bias1, bias2,
              adamw_mode != 0);
  });
}

// Fused Adagrad step (reference csrc/adagrad/cpu_adagrad.cpp).
void ds_adagrad_update(float* params, const float* grads, float* exp_avg_sq,
                       int64_t n, int step, float lr, float eps,
                       float weight_decay) {
  (void)step;
  parallel_for((size_t)n, 1 << 16, [&](size_t s, size_t e) {
    for (size_t i = s; i < e; ++i) {
      float grad = grads[i];
      if (weight_decay > 0.0f) grad += weight_decay * params[i];
      exp_avg_sq[i] += grad * grad;
      params[i] -= lr * grad / (std::sqrt(exp_avg_sq[i]) + eps);
    }
  });
}

}  // extern "C"

"""JIT build of the native host-op library (reference op_builder/builder.py
jit_load, re-done as one g++ -shared compile with a content-hash cache —
no torch extension machinery)."""

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

try:
    from deepspeed_tpu.utils.logging import logger
except Exception:  # standalone use (setup.py AOT build: no jax installed)
    import logging

    logger = logging.getLogger("deepspeed_tpu.native")

_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")
_SOURCES = ("cpu_adam.cpp", "aio.cpp")
_LIB = None


def _cache_dir() -> str:
    base = os.environ.get("DS_TPU_CACHE",
                          os.path.join(tempfile.gettempdir(),
                                       "deepspeed_tpu_native"))
    os.makedirs(base, exist_ok=True)
    return base


def _content_hash() -> str:
    h = hashlib.sha256()
    for src in _SOURCES:
        with open(os.path.join(_CSRC, src), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _prebuilt_path() -> Optional[str]:
    """AOT library shipped by ``setup.py`` with DS_BUILD_OPS=1 (reference
    setup.py:115-163 DS_BUILD_* ahead-of-time builds). Only honoured when
    the content hash matches the installed sources."""
    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "prebuilt",
                     f"libds_tpu_native_{_content_hash()}.so")
    return p if os.path.exists(p) else None


def build(verbose: bool = False, portable: bool = False,
          out_path: Optional[str] = None) -> str:
    """Compile the shared library (content-hashed, idempotent).

    ``portable`` drops ``-march=native`` — required for an AOT artifact
    that ships in a wheel (a native-ISA build can SIGILL on an older
    target CPU); the private JIT cache keeps the native tuning."""
    if out_path is None:
        pre = _prebuilt_path()
        if pre is not None:
            return pre
        out = os.path.join(_cache_dir(),
                           f"libds_tpu_native_{_content_hash()}.so")
    else:
        out = out_path
    if os.path.exists(out):
        return out
    srcs = [os.path.join(_CSRC, s) for s in _SOURCES]
    # per-process tmp name: concurrent first-use builds (one per launcher
    # worker) must not clobber each other's half-written output
    tmp = f"{out}.{os.getpid()}.tmp"
    arch = [] if portable else ["-march=native"]
    cmd = (["g++", "-O3"] + arch + ["-std=c++17", "-shared", "-fPIC",
           "-pthread", "-o", tmp] + srcs)
    if verbose:
        logger.info("building native ops: " + " ".join(cmd))
    try:
        subprocess.run(cmd, check=True, capture_output=not verbose)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        raise RuntimeError(f"native op build failed: {e}") from e
    os.replace(tmp, out)
    logger.info(f"native host ops built: {out}")
    return out


def load_library(build_if_missing: bool = True) -> Optional[ctypes.CDLL]:
    """Load (building on demand) and declare the C API."""
    global _LIB
    if _LIB is not None:
        return _LIB
    path = _prebuilt_path() or os.path.join(
        _cache_dir(), f"libds_tpu_native_{_content_hash()}.so")
    if not os.path.exists(path):
        if not build_if_missing:
            return None
        path = build()
    lib = ctypes.CDLL(path)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.ds_adam_update.argtypes = [
        f32p, f32p, f32p, f32p, ctypes.c_int64, ctypes.c_int,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_int]
    lib.ds_adam_update.restype = None
    lib.ds_adagrad_update.argtypes = [
        f32p, f32p, f32p, ctypes.c_int64, ctypes.c_int, ctypes.c_float,
        ctypes.c_float, ctypes.c_float]
    lib.ds_adagrad_update.restype = None
    lib.ds_aio_handle_create.argtypes = [ctypes.c_int]
    lib.ds_aio_handle_create.restype = ctypes.c_void_p
    lib.ds_aio_handle_destroy.argtypes = [ctypes.c_void_p]
    lib.ds_aio_pread.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_void_p, ctypes.c_int64,
                                 ctypes.c_int64]
    lib.ds_aio_pwrite.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_void_p, ctypes.c_int64,
                                  ctypes.c_int64]
    lib.ds_aio_wait.argtypes = [ctypes.c_void_p]
    lib.ds_aio_wait.restype = ctypes.c_int
    _LIB = lib
    return lib


if __name__ == "__main__":
    build(verbose=True)

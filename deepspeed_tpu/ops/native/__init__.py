"""Native (C++) host ops — loaded lazily; built via
``python -m deepspeed_tpu.ops.native`` (see builder.py)."""


def available() -> bool:
    try:
        from deepspeed_tpu.ops.native.builder import load_library

        return load_library(build_if_missing=False) is not None
    except Exception:
        return False

"""Host-memory parameter streaming (ZeRO-Infinity parameter tier).

Reference counterpart: ``zero/partition_parameters.py:537`` (``remote_device
= "cpu"``) + ``swap_tensor/partitioned_param_swapper.py:35`` — parameters
live off-device and are fetched on use. TPU re-design: parameters are
placed in the accelerator host's memory (``pinned_host`` memory space) and
the compiled step streams each scanned layer's slice into HBM right before
use — ``lax.scan``'s per-iteration slicing happens in host memory, so HBM
only ever holds one layer's working set, and XLA overlaps the copy-in with
the previous layer's compute. Rematerialized backward passes re-fetch the
layer (the reference coordinator's re-gather, parameter_offload.py:384).
"""

import functools

import jax

# jax.memory.Space came and went across versions; TransferToMemoryKind is
# the stable spelling of "same sharding, different memory space" (usable
# inside jit). Exported from jax.sharding in newer releases only.
try:
    from jax.sharding import TransferToMemoryKind as _ToMemKind
except ImportError:
    try:
        from jax._src.sharding_impls import TransferToMemoryKind as _ToMemKind
    except ImportError:
        _ToMemKind = None


@functools.cache
def _host_memory_supported() -> bool:
    # SPMD host-memory placement is a TPU feature; the virtual CPU mesh
    # rejects the placement custom-call, so tests run structure-only
    return _ToMemKind is not None and jax.devices()[0].platform == "tpu"


@jax.custom_vjp
def stream_to_device(x):
    """Copy a (possibly host-resident) array into device memory.

    The backward transfers the cotangent to HOST memory (on TPU): the
    scan's stacked parameter-gradient is then assembled in host memory one
    layer-slice at a time, so neither the full parameters NOR the full
    gradients ever exist in HBM — the ZeRO-Infinity memory equation.
    """
    if not _host_memory_supported():
        return x  # structure-only on hosts without memory spaces
    return jax.device_put(x, _ToMemKind("device"))


def _fwd(x):
    return stream_to_device(x), None


def _bwd(_, g):
    if _host_memory_supported():
        g = jax.device_put(g, _ToMemKind("pinned_host"))
    return (g,)


stream_to_device.defvjp(_fwd, _bwd)


def stream_tree_to_device(tree):
    """``stream_to_device`` over a pytree (flax collection)."""
    return jax.tree.map(stream_to_device, tree)

"""Config-to-model wiring for block-sparse attention.

Capability counterpart of reference
``deepspeed/ops/sparse_attention/sparse_attention_utils.py:1-126``
(SparseAttentionUtils: swap a model's self-attention for
SparseSelfAttention, pad/unpad inputs to the block size) and the
``sparse_attention`` config block parsing at reference
``deepspeed/runtime/config.py:283-466``.

The TPU-native shape of "replace the attention module": our models are
flax dataclass-configured, so instead of monkey-patching torch submodules
the model's *config* carries an optional ``sparse_attention`` field
(a :class:`SparsityConfig`), and the attention module routes on it at
trace time. :func:`apply_sparse_attention` returns a rebuilt model with
that field populated; ``deepspeed_tpu.initialize`` calls it automatically
when the DeepSpeed config has a ``sparse_attention`` block.
"""

import dataclasses
import inspect

import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig,
    SparsityConfig,
    VariableSparsityConfig,
)

# reference runtime/config.py:283 SPARSE_*_MODE constants
SPARSE_MODE_KEY = "mode"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_LOCAL_SLIDING_WINDOW_MODE = "local_sliding_window"

_MODE_TO_CONFIG = {
    SPARSE_DENSE_MODE: DenseSparsityConfig,
    SPARSE_FIXED_MODE: FixedSparsityConfig,
    SPARSE_VARIABLE_MODE: VariableSparsityConfig,
    SPARSE_BIGBIRD_MODE: BigBirdSparsityConfig,
    SPARSE_BSLONGFORMER_MODE: BSLongformerSparsityConfig,
    SPARSE_LOCAL_SLIDING_WINDOW_MODE: LocalSlidingWindowSparsityConfig,
}


def get_sparse_attention_config(param_dict: dict,
                                num_heads: int) -> SparsityConfig:
    """Build a :class:`SparsityConfig` from a DeepSpeed ``sparse_attention``
    config block (reference runtime/config.py:427 get_sparse_attention).

    ``num_heads`` comes from the model, not the JSON — the reference takes
    it at module-construction time the same way.
    """
    if isinstance(param_dict, SparsityConfig):
        return param_dict
    params = dict(param_dict or {})
    mode = params.pop(SPARSE_MODE_KEY, SPARSE_FIXED_MODE)
    # implementation selector, not a layout parameter: "gather" (default,
    # XLA static-gather + MXU einsums), "pallas" (streaming kernel), or
    # "dense" (masked full attention, for testing)
    kernel_impl = params.pop("kernel", None)
    cls = _MODE_TO_CONFIG.get(mode)
    if cls is None:
        raise NotImplementedError(
            f"sparse_attention mode '{mode}' is not supported; choose from "
            f"{sorted(_MODE_TO_CONFIG)}")
    # num_heads comes from the model, never from the JSON — reject it here
    # or cls(num_heads=..., **params) dies with a confusing TypeError
    accepted = set(inspect.signature(cls.__init__).parameters) \
        - {"self", "num_heads"}
    unknown = set(params) - accepted
    if unknown:
        raise ValueError(
            f"sparse_attention ({mode}): unknown keys {sorted(unknown)}; "
            f"accepted: {sorted(accepted)}")
    sc = cls(num_heads=num_heads, **params)
    if kernel_impl is not None:
        if kernel_impl not in ("gather", "pallas", "dense"):
            raise ValueError(
                f"sparse_attention kernel must be 'gather', 'pallas' or "
                f"'dense', got '{kernel_impl}'")
        sc.kernel_impl = kernel_impl
    return sc


def apply_sparse_attention(model, sparse_config):
    """Return ``model`` rebuilt with block-sparse attention enabled.

    ``sparse_config`` is the DeepSpeed ``sparse_attention`` dict (or an
    already-built :class:`SparsityConfig`). The model's config dataclass
    must expose a ``sparse_attention`` field and a ``num_attention_heads``
    (or ``n_head``) count — the BERT encoder and the GPT causal trunk
    (and every family sharing them) here; reference supported-model list:
    sparse_attention_utils.py:37 replace_model_self_attention.
    """
    cfg = getattr(model, "config", None)
    if cfg is None or not any(f.name == "sparse_attention"
                              for f in dataclasses.fields(cfg)):
        raise NotImplementedError(
            f"{type(model).__name__} does not support sparse attention "
            f"injection (its config has no 'sparse_attention' field); "
            f"supported: BertForPreTraining, GPT, and models sharing "
            f"their encoder/trunk")
    num_heads = getattr(cfg, "num_attention_heads",
                        getattr(cfg, "n_head", None))
    if num_heads is None:
        raise ValueError(
            f"cannot inject sparse attention into {type(model).__name__}: "
            f"its config ({type(cfg).__name__}) exposes neither "
            f"'num_attention_heads' nor 'n_head', so the SparsityConfig "
            f"head count cannot be resolved")
    sc = get_sparse_attention_config(sparse_config, num_heads)
    new_cfg = dataclasses.replace(cfg, sparse_attention=sc)
    return model.clone(config=new_cfg)


def pad_to_block_size(block: int, input_ids, attention_mask=None,
                      pad_token_id: int = 0):
    """Pad ``[B, T]`` token inputs on the right so T is a block multiple
    (reference sparse_attention_utils.py:84 pad_to_block_size). Returns
    ``(pad_len, input_ids, attention_mask)``; padded keys are masked out.
    """
    t = input_ids.shape[1]
    pad_len = (-t) % block
    if pad_len == 0:
        return 0, input_ids, attention_mask
    pad = [(0, 0), (0, pad_len)]
    input_ids = jnp.pad(input_ids, pad, constant_values=pad_token_id)
    if attention_mask is None:
        attention_mask = jnp.ones((input_ids.shape[0], t), dtype=bool)
    attention_mask = jnp.pad(attention_mask.astype(bool), pad,
                             constant_values=False)
    return pad_len, input_ids, attention_mask


def unpad_sequence_output(pad_len: int, sequence_output):
    """Strip padding added by :func:`pad_to_block_size` from ``[B, T, ...]``
    model output (reference sparse_attention_utils.py:126)."""
    if pad_len == 0:
        return sequence_output
    return sequence_output[:, :-pad_len]


def ring_decode_params(sparsity_config):
    """``(past_window_blocks, global_tokens, block)`` when the layout's
    DECODE-time visibility is expressible as "a sliding window of whole
    blocks plus a contiguous run of leading global blocks" — the shape a
    ring KV cache can serve exactly — else ``None``.

    Expressible: :class:`LocalSlidingWindowSparsityConfig` (pure causal
    window) and causal :class:`BSLongformerSparsityConfig` whose global
    blocks are a leading contiguous run. BigBird is NOT expressible: its
    per-row random links reach arbitrary past blocks, which a bounded
    ring cannot retain. Fixed/variable patterns' row-block structure
    likewise exceeds window+globals.
    """
    sc = sparsity_config
    if isinstance(sc, LocalSlidingWindowSparsityConfig):
        if sc.attention != "unidirectional":
            return None
        return sc.num_sliding_window_blocks // 2, 0, sc.block
    if isinstance(sc, BSLongformerSparsityConfig):
        if sc.attention != "unidirectional":
            return None
        idx = list(sc.global_block_indices)
        if sc.global_block_end_indices is None:
            spans = [(g, g + 1) for g in idx]
        else:
            spans = list(zip(idx, sc.global_block_end_indices))
        blocks = sorted({b for s, e in spans for b in range(s, e)})
        if blocks != list(range(len(blocks))):
            return None  # globals not a leading contiguous run
        return (sc.num_sliding_window_blocks // 2, len(blocks) * sc.block,
                sc.block)
    return None


def ring_engaged(model_cfg):
    """The ONE decision both the model's decode path and the inference
    engine's divergence warning consult: the ring parameters when this
    model config will decode through the compact layout-aware KV cache,
    else ``None`` (dense decode). Keeping it here prevents the two call
    sites from drifting — a stale copy would warn "decodes DENSE" while
    the model rings, or stay silent while it fell back."""
    sc = getattr(model_cfg, "sparse_attention", None)
    if sc is None:
        return None
    if getattr(model_cfg, "sparse_kv_cache", False) not in ("auto", True):
        return None
    demanded = getattr(model_cfg, "sparse_kv_cache", False) is True
    ring = ring_decode_params(sc)
    if ring is None:
        if demanded:
            _decline_demanded_ring(
                f"layout {type(sc).__name__} has no ring expression")
        return None
    w_blk, g_tok, blk = ring
    if not demanded and g_tok + (w_blk + 1) * blk >= model_cfg.n_positions:
        # "auto" means "ring only when it helps": a ring no smaller than
        # the dense cache buys nothing, so auto silently declines.
        # sparse_kv_cache=True is a DEMAND — the caller wants the ring's
        # exact training-sparse decode math (and its chunked-prefill /
        # streaming semantics) regardless of size, so True engages here;
        # only layouts with no ring expression at all decline above.
        return None
    return ring


def ring_storage_len(model_cfg, ring) -> int:
    """Physical ring capacity in tokens: the ``w_blk + 1`` blocks decode
    visibility needs, plus ``kv_cache_slack_blocks`` extra STORAGE blocks.

    Slack is semantically invisible — visibility is positional (an
    entry's ``slot_pos`` against the query's window), so extra blocks
    only delay overwrite — but it is what makes an UNALIGNED multi-token
    mid-stream pass exact: with one slack block, a pass of at most
    ``block`` tokens can never evict an entry that any of its own
    columns (or any post-rewind query) still needs. The speculative-
    decode verify forward (inference/scheduler.py) is exactly such a
    pass; chunked prefill instead splits at block boundaries and needs
    no slack. The ONE definition of ring storage size — the model's
    cache allocation and the engine's span math both call this."""
    w_blk, g_tok, blk = ring
    slack = int(getattr(model_cfg, "kv_cache_slack_blocks", 0) or 0)
    return (w_blk + 1 + slack) * blk


# Newest-last reasons every time an EXPLICIT sparse_kv_cache=True was
# declined (test/debug hook for the warn-and-record below; "auto" declines
# stay silent — auto means "ring only when it helps").
RING_DECLINES: list = []


def _decline_demanded_ring(reason: str) -> None:
    """sparse_kv_cache=True is a demand, not a hint: record + warn instead
    of silently decoding dense, so the config cannot lie about what the
    cache is doing (dense decode consults MORE keys than ring-sparse
    training did — docs/DIVERGENCES.md, Inference section)."""
    import warnings

    from deepspeed_tpu.telemetry.bus import KIND_RING_DECLINE, publish

    RING_DECLINES.append(reason)
    publish(KIND_RING_DECLINE, severity="warning", reason=reason)
    warnings.warn(
        "sparse_kv_cache=True but the ring KV cache is NOT engaged; decode "
        f"falls back to DENSE attention: {reason}", RuntimeWarning,
        stacklevel=3)

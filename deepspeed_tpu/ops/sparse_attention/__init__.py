"""Block-sparse attention for TPU.

Capability surface of reference ``deepspeed/ops/sparse_attention`` (Triton
block-sparse matmul/softmax + SparsityConfig family,
``ops/sparse_attention/sparsity_config.py:9-743``,
``sparse_self_attention.py:11``) rebuilt as a Pallas splash-style kernel:
the sparsity layout is a static block mask compiled into the kernel's block
index lists, so only active [block, block] tiles are ever computed.
"""

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (  # noqa: F401
    SparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    VariableSparsityConfig,
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    LocalSlidingWindowSparsityConfig,
)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (  # noqa: F401
    SparseSelfAttention,
    block_sparse_attention,
    dense_blocksparse_attention,
    gathered_blocksparse_attention,
)

"""Sparsity-pattern configs producing block-level attention layouts.

Same config family and constructor surface as the reference
(``deepspeed/ops/sparse_attention/sparsity_config.py:9-743``): Dense, Fixed,
Variable, BigBird, BSLongformer, LocalSlidingWindow. A layout is a host-side
``np.ndarray`` of shape ``[num_layout_heads, num_blocks, num_blocks]`` with
1 marking an active [block, block] tile — static data baked into the Pallas
kernel's block index lists at trace time (never a device tensor).
"""

from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Abstract base holding properties shared by all patterns
    (reference sparsity_config.py:9)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence length {seq_len} must be divisible by block size "
                f"{self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks),
                        dtype=np.int64)

    def check_and_propagate_first_head_layout(
            self, layout: np.ndarray) -> np.ndarray:
        """When all heads share one layout, broadcast head 0 to the rest
        (reference sparsity_config.py:59)."""
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError

    def _apply_direction(self, layout: np.ndarray,
                         attention: str) -> np.ndarray:
        """Unidirectional patterns never attend above the block diagonal."""
        if attention == "unidirectional":
            num_blocks = layout.shape[1]
            tril = np.tril(np.ones((num_blocks, num_blocks), dtype=np.int64))
            layout &= tril[None]
        return layout


class DenseSparsityConfig(SparsityConfig):
    """All blocks active; kept for comparison (reference :63)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Fixed local windows + fixed global representative blocks
    (reference :94, the pattern of the Sparse Transformer paper)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"num_local_blocks {num_local_blocks} must be divisible by "
                f"num_global_blocks {num_global_blocks}")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "attention must be uni- or bidirectional")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError(
                "horizontal global attention requires bidirectional attention")
        max_patterns = num_local_blocks // num_global_blocks
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "multiple global patterns require different_layout_per_head")
        if num_different_global_patterns > max_patterns:
            raise ValueError(
                f"num_different_global_patterns "
                f"{num_different_global_patterns} exceeds "
                f"num_local_blocks/num_global_blocks = {max_patterns}")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        for h in range(self.num_layout_heads):
            # local windows: dense [window, window] squares on the diagonal
            for start in range(0, num_blocks, self.num_local_blocks):
                end = min(start + self.num_local_blocks, num_blocks)
                layout[h, start:end, start:end] = 1
            # global blocks: the h-th pattern picks a different representative
            # slot inside each local window, counted from the window's end
            offset = (1 + h % self.num_different_global_patterns) \
                * self.num_global_blocks
            for start in range(0, num_blocks, self.num_local_blocks):
                win_end = min(start + self.num_local_blocks, num_blocks)
                g = min(win_end - offset, num_blocks - self.num_global_blocks)
                g = max(g, start)
                g_end = min(g + self.num_global_blocks, num_blocks)
                # all later rows attend to this window's representative
                layout[h, g_end:, g:g_end] = 1
                if self.horizontal_global_attention:
                    layout[h, g:g_end, :] = 1
            layout[h] = self._apply_direction(layout[h:h + 1],
                                              self.attention)[0]
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """User-shaped pattern: random blocks + variable-size local windows +
    explicit global block indices (reference :243)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        local_window_blocks = local_window_blocks or [4]
        global_block_indices = (
            [0] if global_block_indices is None else global_block_indices)
        if global_block_end_indices is not None:
            if len(global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must "
                    "have the same length")
            for s, e in zip(global_block_indices, global_block_end_indices):
                if s >= e:
                    raise ValueError(
                        f"global block start {s} must be < end {e}")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "attention must be uni- or bidirectional")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError(
                "horizontal global attention requires bidirectional attention")
        # random blocks differ per head only if layouts differ per head;
        # a single shared layout still gets one random set
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks
        self.global_block_indices = global_block_indices
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        rng = np.random.RandomState(self.seed)
        for h in range(self.num_layout_heads):
            # variable local windows: sizes from the list, last size repeats
            start = 0
            i = 0
            while start < num_blocks:
                size = self.local_window_blocks[
                    min(i, len(self.local_window_blocks) - 1)]
                end = min(start + size, num_blocks)
                layout[h, start:end, start:end] = 1
                start = end
                i += 1
            # global blocks: rows and columns of the given indices/ranges
            if self.global_block_end_indices is None:
                spans = [(g, g + 1) for g in self.global_block_indices]
            else:
                spans = list(zip(self.global_block_indices,
                                 self.global_block_end_indices))
            for s, e in spans:
                s, e = min(s, num_blocks), min(e, num_blocks)
                layout[h, :, s:e] = 1
                if self.horizontal_global_attention:
                    layout[h, s:e, :] = 1
            # random blocks per row; unidirectional draws from the past so
            # the tril in _apply_direction doesn't discard the picks
            for row in range(num_blocks):
                pool = row + 1 if self.attention == "unidirectional" \
                    else num_blocks
                cols = rng.choice(pool,
                                  size=min(self.num_random_blocks, pool),
                                  replace=False)
                layout[h, row, cols] = 1
            layout[h] = self._apply_direction(layout[h:h + 1],
                                              self.attention)[0]
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird: random + sliding window + leading global blocks
    (reference :426)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 attention: str = "bidirectional", seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "attention must be uni- or bidirectional")
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        if num_blocks < self.num_sliding_window_blocks:
            raise ValueError(
                f"num_sliding_window_blocks {self.num_sliding_window_blocks} "
                f"exceeds total blocks {num_blocks}")
        rng = np.random.RandomState(self.seed)
        w = self.num_sliding_window_blocks // 2
        g = min(self.num_global_blocks, num_blocks)
        for h in range(self.num_layout_heads):
            for row in range(num_blocks):
                lo, hi = max(0, row - w), min(row + w + 1, num_blocks)
                layout[h, row, lo:hi] = 1
                # random long-range links; unidirectional draws from the past
                pool = row + 1 if self.attention == "unidirectional" \
                    else num_blocks
                pool = max(pool, 1)
                cols = rng.choice(pool,
                                  size=min(self.num_random_blocks, pool),
                                  replace=False)
                layout[h, row, cols] = 1
            layout[h, :, :g] = 1  # everyone attends to leading globals
            layout[h, :g, :] = 1  # leading globals attend to everyone
            layout[h] = self._apply_direction(layout[h:h + 1],
                                              self.attention)[0]
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + chosen global blocks
    (reference :567)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        global_block_indices = (
            [0] if global_block_indices is None else global_block_indices)
        if global_block_end_indices is not None:
            if len(global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must "
                    "have the same length")
            for s, e in zip(global_block_indices, global_block_end_indices):
                if s >= e:
                    raise ValueError(
                        f"global block start {s} must be < end {e}")
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for row in range(num_blocks):
                lo, hi = max(0, row - w), min(row + w + 1, num_blocks)
                layout[h, row, lo:hi] = 1
            if self.global_block_end_indices is None:
                spans = [(g, g + 1) for g in self.global_block_indices]
            else:
                spans = list(zip(self.global_block_indices,
                                 self.global_block_end_indices))
            for s, e in spans:
                s, e = min(s, num_blocks), min(e, num_blocks)
                layout[h, :, s:e] = 1
                layout[h, s:e, :] = 1
            layout[h] = self._apply_direction(layout[h:h + 1],
                                              self.attention)[0]
        return self.check_and_propagate_first_head_layout(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding-window pattern (reference :690)."""

    def __init__(self, num_heads: int, block: int = 16,
                 num_sliding_window_blocks: int = 3,
                 attention: str = "unidirectional"):
        super().__init__(num_heads, block)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        if num_blocks < self.num_sliding_window_blocks:
            raise ValueError(
                f"num_sliding_window_blocks {self.num_sliding_window_blocks} "
                f"exceeds total blocks {num_blocks}")
        w = self.num_sliding_window_blocks // 2
        for row in range(num_blocks):
            lo = max(0, row - w)
            hi = min(row + w + 1, num_blocks) \
                if self.attention == "bidirectional" else row + 1
            layout[0, row, lo:hi] = 1
        return self.check_and_propagate_first_head_layout(layout)

"""Splash-style block-sparse attention kernel + module.

TPU-native replacement for the reference Triton block-sparse path
(``ops/sparse_attention/matmul.py:212`` SDD/DSD/DDS, ``softmax.py:142``,
``sparse_self_attention.py:11``). Instead of materializing block-sparse
score matrices through three separate matmul/softmax launches, one Pallas
kernel streams only the ACTIVE key blocks of each query row (their indices
are static host-side data derived from the layout) with online-softmax
rescaling — the sparse analogue of flash attention, O(active_blocks) compute
and O(seq) memory.

Inputs are ``[batch, seq, heads, head_dim]``. The layout is a
``[heads, num_blocks, num_blocks]`` 0/1 array from a
:class:`~deepspeed_tpu.ops.sparse_attention.SparsityConfig`.
"""

import collections
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from deepspeed_tpu.ops.pallas.common import (
    LSE_LANES,
    NEG_INF,
    interpret as _interpret,
)


def _pad_lanes(n: int, mult: int = 128) -> int:
    return ((n + mult - 1) // mult) * mult


# index-table rows are replicated over 8 sublanes so their [8, width] tiles
# satisfy TPU Mosaic lowering (same trick as the LSE_LANES rows)
IDX_SUBLANES = 8


def _build_index_tables(layout: np.ndarray, num_heads: int):
    """Static per-row active-block index lists, padded with -1.

    Returns ``(kidx [H, nq, IDX_SUBLANES, width_k], n_k)`` — active key
    blocks per query row and the true max active count bounding the kernel
    loop — and the analogous ``(qidx [H, nk, IDX_SUBLANES, width_q], n_q)``
    for the dkv iteration order. Table width is lane-padded to 128; only the
    first n_* entries are real.
    """
    h_layout, nq, nk = layout.shape
    if h_layout not in (1, num_heads):
        raise ValueError(
            f"layout has {h_layout} head layouts; expected 1 or {num_heads}")
    layout = np.broadcast_to(layout, (num_heads, nq, nk)) \
        if h_layout == 1 else layout

    def tables(mat_rows):
        counts = mat_rows.sum(axis=-1)
        n_iter = max(int(counts.max()), 1)
        width = _pad_lanes(n_iter, 128)
        out = np.full((num_heads, mat_rows.shape[1], width), -1,
                      dtype=np.int32)
        for h in range(num_heads):
            for r in range(mat_rows.shape[1]):
                idx = np.nonzero(mat_rows[h, r])[0]
                out[h, r, :len(idx)] = idx
        out = np.repeat(out[:, :, None, :], IDX_SUBLANES, axis=2)
        return out, n_iter

    kidx, n_k = tables(layout)
    qidx, n_q = tables(layout.transpose(0, 2, 1))
    return kidx, n_k, qidx, n_q


def _select_idx(row, a, width):
    """Scalar row[a] from a [1, width] vector without dynamic lane indexing."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, width), 1)
    return jnp.sum(jnp.where(lane == a, row, 0))


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, kidx_ref, o_ref, lse_ref, *, scale,
                causal, block, width_k, n_k):
    bq, d = q_ref.shape
    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32) * scale
    row = kidx_ref[...][0:1, :]  # [1, width_k]

    m = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 0)

    def body(a, carry):
        m, l, acc = carry
        j = _select_idx(row, a, width_k)
        valid = j >= 0
        jc = jnp.maximum(j, 0)
        k_blk = k_ref[pl.ds(jc * block, block), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(jc * block, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            k_pos = jc * block + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # rows with no visible key yet (m_new still -inf) must contribute
        # nothing: exp(-inf - -inf) would be 1, leaking masked blocks
        p = jnp.where(m_new > 0.5 * NEG_INF, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_k, body, (m, l, acc))
    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[...] = (acc / l_safe).astype(o_ref.dtype)
    lse = jnp.where(l > 0, m + jnp.log(l_safe), NEG_INF)
    lse_ref[...] = jnp.broadcast_to(lse, (bq, LSE_LANES))


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, kidx_ref,
               dq_ref, *, scale, causal, block, width_k, n_k):
    bq, d = q_ref.shape
    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)
    lse = lse_ref[...][:, :1]
    delta = delta_ref[...][:, :1]
    row = kidx_ref[...][0:1, :]
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 0)
    dq = jnp.zeros((bq, d), jnp.float32)

    def body(a, dq):
        j = _select_idx(row, a, width_k)
        valid = j >= 0
        jc = jnp.maximum(j, 0)
        k_blk = k_ref[pl.ds(jc * block, block), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(jc * block, block), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            k_pos = jc * block + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.where(lse > 0.5 * NEG_INF, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + scale * jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n_k, body, dq)
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qidx_ref,
                dk_ref, dv_ref, *, scale, causal, block, width_q, n_q):
    bk, d = k_ref.shape
    ki = pl.program_id(1)
    k = k_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    row = qidx_ref[...][0:1, :]
    k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (block, bk), 1)
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)

    def body(a, carry):
        dk, dv = carry
        i = _select_idx(row, a, width_q)
        valid = i >= 0
        ic = jnp.maximum(i, 0)
        q_blk = q_ref[pl.ds(ic * block, block), :].astype(jnp.float32)
        do_blk = do_ref[pl.ds(ic * block, block), :].astype(jnp.float32)
        lse_blk = lse_ref[pl.ds(ic * block, block), :][:, :1]
        delta_blk = delta_ref[pl.ds(ic * block, block), :][:, :1]
        s = scale * jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            q_pos = ic * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, bk), 0)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.where(lse_blk > 0.5 * NEG_INF, jnp.exp(s - lse_blk), 0.0)
        dv = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk)
        dk = dk + scale * jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(0, n_q, body, (dk, dv))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# op factory (tables are trace-time constants; cached per layout, bounded)
# ---------------------------------------------------------------------------
_OP_CACHE = collections.OrderedDict()
_OP_CACHE_MAX = 64


def _build_op(layout, num_heads, scale, causal, block):
    kidx, n_k, qidx, n_q = _build_index_tables(layout, num_heads)
    h, nq, _, width_k = kidx.shape
    _, nk, _, width_q = qidx.shape
    # keep the index tables as NUMPY in the closure: ops are cached across
    # traces, and a jnp conversion done while some jit is tracing would bake
    # that trace's tracer into the cache (leaks into every later trace)

    def fwd(q, k, v):
        b, t, heads, d = q.shape
        bh = b * heads

        def flat(x):
            return x.transpose(0, 2, 1, 3).reshape(bh, t, d)

        o, lse = pl.pallas_call(
            functools.partial(_fwd_kernel, scale=scale, causal=causal,
                              block=block, width_k=width_k, n_k=n_k),
            grid=(bh, nq),
            in_specs=[
                pl.BlockSpec((None, block, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, None, IDX_SUBLANES, width_k),
                             lambda i, j: (i % h, j, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, block, LSE_LANES),
                             lambda i, j: (i, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, t, d), q.dtype),
                jax.ShapeDtypeStruct((bh, t, LSE_LANES), jnp.float32),
            ],
            interpret=_interpret(),
        )(flat(q), flat(k), flat(v), jnp.asarray(kidx))
        return o, lse

    @jax.custom_vjp
    def op(q, k, v):
        b, t, heads, d = q.shape
        o, _ = fwd(q, k, v)
        return o.reshape(b, heads, t, d).transpose(0, 2, 1, 3)

    def op_fwd(q, k, v):
        b, t, heads, d = q.shape
        o, lse = fwd(q, k, v)
        return (o.reshape(b, heads, t, d).transpose(0, 2, 1, 3),
                (q, k, v, o, lse))

    def op_bwd(res, g):
        q, k, v, of, lse = res
        b, t, heads, d = q.shape
        bh = b * heads

        def flat(x):
            return x.transpose(0, 2, 1, 3).reshape(bh, t, d)

        qf, kf, vf = map(flat, (q, k, v))
        dof = flat(g)
        delta = jnp.sum(of.astype(jnp.float32) * dof.astype(jnp.float32),
                        axis=-1)
        delta = jnp.broadcast_to(delta[..., None],
                                 delta.shape + (LSE_LANES,))

        dq = pl.pallas_call(
            functools.partial(_dq_kernel, scale=scale, causal=causal,
                              block=block, width_k=width_k, n_k=n_k),
            grid=(bh, nq),
            in_specs=[
                pl.BlockSpec((None, block, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, block, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, block, LSE_LANES),
                             lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, block, LSE_LANES),
                             lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, None, IDX_SUBLANES, width_k),
                             lambda i, j: (i % h, j, 0, 0)),
            ],
            out_specs=pl.BlockSpec((None, block, d), lambda i, j: (i, j, 0)),
            out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            interpret=_interpret(),
        )(qf, kf, vf, dof, lse, delta, jnp.asarray(kidx))

        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, scale=scale, causal=causal,
                              block=block, width_q=width_q, n_q=n_q),
            grid=(bh, nk),
            in_specs=[
                pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, block, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, block, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, t, LSE_LANES), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, t, LSE_LANES), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, None, IDX_SUBLANES, width_q),
                             lambda i, j: (i % h, j, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, block, d), lambda i, j: (i, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, t, d), q.dtype),
                jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            ],
            interpret=_interpret(),
        )(qf, kf, vf, dof, lse, delta, jnp.asarray(qidx))

        def unflat(x):
            return x.reshape(b, heads, t, d).transpose(0, 2, 1, 3)

        return unflat(dq), unflat(dk), unflat(dv)

    op.defvjp(op_fwd, op_bwd)
    return op


def block_sparse_attention(q, k, v, layout, *, block: int,
                           causal: bool = False, scale: float = None):
    """Attention over ``[batch, seq, heads, head_dim]`` restricted to the
    active blocks of ``layout`` ([heads or 1, nq, nk] 0/1 array)."""
    b, t, heads, d = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    layout = np.asarray(layout)
    if t != layout.shape[1] * block:
        raise ValueError(
            f"layout covers {layout.shape[1] * block} positions, "
            f"inputs have {t}")
    key = (layout.tobytes(), layout.shape, str(layout.dtype), heads,
           float(scale), bool(causal), int(block))
    op = _OP_CACHE.get(key)
    if op is None:
        op = _build_op(layout, heads, float(scale), bool(causal), int(block))
        _OP_CACHE[key] = op
        while len(_OP_CACHE) > _OP_CACHE_MAX:
            _OP_CACHE.popitem(last=False)
    else:
        _OP_CACHE.move_to_end(key)
    return op(q, k, v)


def _partition_rows(counts: np.ndarray, nk: int):
    """Split query-block rows into a LIGHT set (narrow, gather path) and a
    HEAVY set (wide, dense path) minimizing total key-block work.

    Sparsity layouts are bimodal: banded rows touch a handful of blocks
    while "global" rows (BigBird/Longformer global tokens, fixed-pattern
    summary blocks) touch every block. A single gather table padded to the
    max row width silently degenerates to dense-everything, so pick the
    width cutoff that minimizes ``W_light * n_light + nk * n_heavy``,
    where ``nk`` is the TOTAL key-block count a dense-path row pays for.
    ``counts`` is the per-row active-block count, max-reduced over head
    layouts. Returns (light_rows, heavy_rows) as sorted index arrays.
    """
    nq = counts.shape[0]
    order = np.argsort(counts)           # ascending width
    sorted_counts = counts[order]
    best_cost, best_split = None, nq     # split = first heavy position
    for split in range(nq + 1):
        w_light = int(sorted_counts[split - 1]) if split else 0
        cost = w_light * split + (nq - split) * nk
        if best_cost is None or cost < best_cost:
            best_cost, best_split = cost, split
    light = np.sort(order[:best_split])
    heavy = np.sort(order[best_split:])
    return light, heavy


def _compact_index_tables(layout: np.ndarray, rows: np.ndarray):
    """Active key-block lists for the given rows, at their TRUE max width
    (no lane padding — the gather path's cost is linear in this width).
    ``layout`` is [hL, nq, nk]; returns ``idx [hL, len(rows), W]`` int32,
    -1 padded."""
    h_layout = layout.shape[0]
    width = max(int(layout[:, rows].sum(axis=-1).max()), 1) if len(rows) \
        else 1
    out = np.full((h_layout, len(rows), width), -1, dtype=np.int32)
    for h in range(h_layout):
        for j, r in enumerate(rows):
            nz = np.nonzero(layout[h, r])[0]
            out[h, j, :len(nz)] = nz
    return out


def gathered_blocksparse_attention(q, k, v, layout, *, block: int,
                                   causal: bool = False, scale: float = None,
                                   key_padding_mask=None, attn_mask=None,
                                   key_padding_mask_mode: str = "add",
                                   attn_mask_mode: str = "mul"):
    """XLA-native block-sparse attention: gather each query row's active
    K/V blocks with STATIC indices, then dense batched einsums over the
    gathered width; wide "global" rows are split off and computed densely.

    The TPU-first formulation of the reference's Triton SDD/DSD launches
    (``ops/sparse_attention/matmul.py:212``): on TPU the win comes from
    keeping the contraction on the MXU — a static gather feeding batched
    [block, W*block] matmuls runs at matmul rate, while a hand-scheduled
    streaming kernel is DMA-latency-bound. Autodiff works through it (XLA
    emits the gather transpose), element masks fold in by gathering mask
    blocks with the same indices, and the light/heavy row split keeps one
    BigBird global row from padding the whole table to dense.
    """
    b, t, heads, d = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    layout = np.asarray(layout)
    h_layout, nq, nk = layout.shape
    if h_layout not in (1, heads):
        raise ValueError(
            f"layout has {h_layout} head layouts; expected 1 or {heads}")
    if t != nq * block:
        raise ValueError(
            f"layout covers {nq * block} positions, inputs have {t}")

    counts = layout.sum(axis=-1).max(axis=0)          # [nq], max over heads
    light_rows, heavy_rows = _partition_rows(counts, nk)

    dtype = q.dtype
    neg = jnp.float32(NEG_INF)
    # block views: [B, H, n, block, D]
    qb = q.reshape(b, nq, block, heads, d).transpose(0, 3, 1, 2, 4)
    kb = k.reshape(b, nq, block, heads, d).transpose(0, 3, 1, 2, 4)
    vb = v.reshape(b, nq, block, heads, d).transpose(0, 3, 1, 2, 4)
    kpb = None
    if key_padding_mask is not None:
        kpb = jnp.asarray(key_padding_mask).reshape(b, nq, block)
    amp = None
    if attn_mask is not None:
        am = jnp.asarray(attn_mask)                   # [T, T]
        amp = am.reshape(nq, block, nq, block)

    def softmax_rows(s, row_shape):
        """Masked softmax over the flattened key axes, NaN-safe for rows
        whose every key is masked (possible under padding masks)."""
        sf = s.reshape(row_shape)
        m = jnp.max(sf, axis=-1, keepdims=True)
        e = jnp.exp(sf - jax.lax.stop_gradient(jnp.maximum(m, neg / 2)))
        denom = jnp.maximum(jnp.sum(e, axis=-1, keepdims=True), 1e-30)
        return (e / denom).astype(dtype).reshape(s.shape)

    def apply_kpm(s, kp):                              # kp: [B, ..., block]
        if key_padding_mask_mode == "mul":
            return jnp.where(kp > 0, s, neg)
        return s + kp.astype(jnp.float32)

    def apply_am(s, am_part):
        if attn_mask_mode == "mul":
            return jnp.where(am_part > 0, s, neg)
        return s + am_part.astype(jnp.float32)

    out_parts, out_rows = [], []

    if len(light_rows):
        idx = _compact_index_tables(layout, light_rows)  # [hL, nL, W] static
        w = idx.shape[-1]
        nl = len(light_rows)
        gidx = jnp.asarray(np.maximum(idx, 0))
        ql = qb[:, :, light_rows]                     # [B, H, nL, block, D]
        if h_layout == 1:
            kg = kb[:, :, gidx[0]]                    # [B, H, nL, W, block, D]
            vg = vb[:, :, gidx[0]]
        else:
            gather = jax.vmap(lambda xb_h, idx_h: xb_h[:, idx_h],
                              in_axes=(1, 0), out_axes=1)
            kg = gather(kb, gidx)
            vg = gather(vb, gidx)
        s = jnp.einsum("bhqid,bhqwjd->bhqiwj", ql, kg,
                       preferred_element_type=jnp.float32) * scale
        valid = idx >= 0                              # [hL, nL, W] static
        s = jnp.where(jnp.asarray(valid)[None, :, :, None, :, None], s, neg)
        if causal:
            q_pos = (light_rows[:, None] * block
                     + np.arange(block)[None, :])     # [nL, block]
            k_pos = idx[..., None] * block + np.arange(block)
            cm = (k_pos[:, :, None, :, :]
                  <= q_pos[None, :, :, None, None])   # [hL,nL,block,W,block]
            s = jnp.where(jnp.asarray(cm)[None], s, neg)
        if amp is not None:
            flat = amp.transpose(0, 2, 1, 3).reshape(nq * nq, block, block)
            pair = light_rows[None, :, None] * nq + np.maximum(idx, 0)
            am_g = flat[jnp.asarray(pair)]            # [hL,nL,W,block,block]
            s = apply_am(s, am_g.transpose(0, 1, 3, 2, 4)[None])
        if kpb is not None:
            if h_layout == 1:
                kp_g = kpb[:, gidx[0]][:, None]       # [B,1,nL,W,block]
            else:
                kp_g = jax.vmap(lambda idx_h: kpb[:, idx_h])(gidx)
                kp_g = kp_g.transpose(1, 0, 2, 3, 4)
            s = apply_kpm(s, kp_g[:, :, :, None])
        p = softmax_rows(s, (b, heads, nl, block, w * block))
        o = jnp.einsum("bhqiwj,bhqwjd->bhqid", p, vg)
        out_parts.append(o)
        out_rows.append(light_rows)

    if len(heavy_rows):
        nh = len(heavy_rows)
        qh = qb[:, :, heavy_rows]                     # [B, H, nH, block, D]
        s = jnp.einsum("bhrid,bhnjd->bhrinj", qh, kb,
                       preferred_element_type=jnp.float32) * scale
        row_mask = layout[:, heavy_rows].astype(bool)  # [hL, nH, nk] static
        s = jnp.where(jnp.asarray(row_mask)[None, :, :, None, :, None],
                      s, neg)
        if causal:
            q_pos = (heavy_rows[:, None] * block
                     + np.arange(block)[None, :])     # [nH, block]
            k_pos = (np.arange(nk)[:, None] * block
                     + np.arange(block)[None, :])     # [nk, block]
            cm = (k_pos[None, None, :, :]
                  <= q_pos[:, :, None, None])         # [nH, block, nk, block]
            s = jnp.where(jnp.asarray(cm)[None, None], s, neg)
        if amp is not None:
            am_h = amp[heavy_rows]                    # [nH, block, nq, block]
            s = apply_am(s, am_h[None, None])
        if kpb is not None:
            s = apply_kpm(s, kpb[:, None, None, None])
        p = softmax_rows(s, (b, heads, nh, block, nk * block))
        o = jnp.einsum("bhrinj,bhnjd->bhrid", p, vb)
        out_parts.append(o)
        out_rows.append(heavy_rows)

    o = out_parts[0] if len(out_parts) == 1 else \
        jnp.concatenate(out_parts, axis=2)
    order = np.concatenate(out_rows)
    if not np.array_equal(order, np.arange(nq)):
        o = jnp.take(o, jnp.asarray(np.argsort(order)), axis=2)
    return o.transpose(0, 2, 3, 1, 4).reshape(b, t, heads, d).astype(dtype)


def dense_blocksparse_attention(q, k, v, layout, *, block: int,
                                causal: bool = False, scale: float = None,
                                key_padding_mask=None, attn_mask=None,
                                key_padding_mask_mode: str = "add",
                                attn_mask_mode: str = "mul"):
    """XLA-native reference path: expands the block layout to an element mask.

    Used for correctness testing and for the mask-bearing cases
    (key_padding_mask / attn_mask, reference sparse_self_attention.py:103)
    the streaming kernel does not take.
    """
    b, t, heads, d = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    layout = np.asarray(layout)
    mask = np.kron(layout, np.ones((block, block), dtype=layout.dtype))
    mask = jnp.asarray(np.broadcast_to(mask, (heads,) + mask.shape[1:]))

    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    neg = jnp.float32(NEG_INF)
    s = jnp.where(mask[None] > 0, s, neg)
    if causal:
        cm = jnp.tril(jnp.ones((t, t), dtype=bool))
        s = jnp.where(cm[None, None], s, neg)
    if attn_mask is not None:
        am = jnp.asarray(attn_mask)
        if attn_mask_mode == "mul":
            s = jnp.where(am[None, None] > 0, s, neg)
        else:
            s = s + am[None, None]
    if key_padding_mask is not None:
        kpm = jnp.asarray(key_padding_mask)  # [b, t]
        if key_padding_mask_mode == "mul":
            s = jnp.where(kpm[:, None, None, :] > 0, s, neg)
        else:
            s = s + kpm[:, None, None, :]
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)


class SparseSelfAttention:
    """Module-level API of reference ``sparse_self_attention.py:11``.

    Computes scaled dot-product attention under the config's block-sparsity
    layout. Routes to the streaming Pallas kernel when no element-level masks
    are given, and to the XLA dense-masked path otherwise.
    """

    def __init__(self, sparsity_config, key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul", max_seq_length: int = 2048,
                 impl: str = None):
        self.sparsity_config = sparsity_config
        if key_padding_mask_mode not in ("add", "mul"):
            raise ValueError("key_padding_mask_mode must be 'add' or 'mul'")
        if attn_mask_mode not in ("add", "mul"):
            raise ValueError("attn_mask_mode must be 'add' or 'mul'")
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        # "gather" (default): static-index K/V block gather + dense batched
        # einsums — keeps the contraction on the MXU and measures ~an order
        # of magnitude faster than the streaming Pallas kernel on real
        # chips (benchmarks/sparse_attention_results.json). "pallas": the
        # streaming kernel (O(seq) memory, no gathered buffer — the choice
        # when W*block activations don't fit). "dense": masked full
        # attention, for testing.
        if impl is None:
            impl = getattr(sparsity_config, "kernel_impl", None) or "gather"
        if impl not in ("gather", "pallas", "dense"):
            raise ValueError("impl must be 'gather', 'pallas' or 'dense'")
        self.impl = impl
        self._layouts = {}

    def get_layout(self, seq_len: int) -> np.ndarray:
        if seq_len > self.max_seq_length:
            raise ValueError(
                f"seq_len {seq_len} exceeds max_seq_length "
                f"{self.max_seq_length}")
        if seq_len not in self._layouts:
            self._layouts[seq_len] = \
                self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, query, key, value, key_padding_mask=None,
                 attn_mask=None, causal=None):
        b, t, h, d = query.shape
        layout = self.get_layout(t)
        if causal is None:
            causal = getattr(self.sparsity_config, "attention",
                             "bidirectional") == "unidirectional"
        block = self.sparsity_config.block
        if self.impl == "gather":
            return gathered_blocksparse_attention(
                query, key, value, layout, block=block, causal=causal,
                key_padding_mask=key_padding_mask, attn_mask=attn_mask,
                key_padding_mask_mode=self.key_padding_mask_mode,
                attn_mask_mode=self.attn_mask_mode)
        if self.impl == "pallas":
            if key_padding_mask is None and attn_mask is None:
                return block_sparse_attention(
                    query, key, value, layout, block=block, causal=causal)
            # the streaming kernel takes no element-level masks; an explicit
            # pallas selection degrading to the quadratic masked-dense path
            # must not happen silently (O(T^2) scores at long seq)
            import warnings

            warnings.warn(
                "sparse_attention kernel='pallas' with an element mask "
                "falls back to masked DENSE attention (full [T, T] "
                "scores); use the default 'gather' impl for masked "
                "inputs", stacklevel=2)
        return dense_blocksparse_attention(
            query, key, value, layout, block=block,
            causal=causal, key_padding_mask=key_padding_mask,
            attn_mask=attn_mask,
            key_padding_mask_mode=self.key_padding_mask_mode,
            attn_mask_mode=self.attn_mask_mode)

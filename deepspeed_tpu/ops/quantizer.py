"""Quantization ops.

Parity with reference ``csrc/quantization/quantizer.cu`` via
``ops/quantizer/quantizer.py:27`` (``ds_quantize_fp32/fp16``, stochastic-
rounding ``ds_sr_quantize_*`` and asymmetric ``*_asym`` variants): grouped
symmetric/asymmetric fake-quantization and int8 extraction.

These are elementwise + per-group reductions — exactly what XLA fuses into
single VPU passes, so the implementation is pure jnp (a Pallas kernel would
re-derive the same schedule). Stochastic rounding uses jax PRNG keys instead
of the CUDA Philox state.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _grouped(x: jnp.ndarray, num_groups: int) -> jnp.ndarray:
    n = x.size
    assert n % num_groups == 0, (
        f"size {n} not divisible into {num_groups} groups")
    return x.reshape(num_groups, n // num_groups)


def quantize(
    x: jnp.ndarray,
    num_bits: int = 8,
    num_groups: int = 1,
    symmetric: bool = True,
    stochastic: bool = False,
    rng: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, Optional[jnp.ndarray]]:
    """Quantize to ``(q_int, scale, zero_point)`` with per-group scales.

    Symmetric: q = round(x/scale), scale = max|x| / qmax (reference
    ds_quantize). Asymmetric: affine with zero point (reference *_asym).
    ``stochastic`` adds uniform noise in [-0.5, 0.5) before rounding
    (reference ds_sr_quantize stochastic rounding).
    """
    orig_shape = x.shape
    g = _grouped(x.astype(jnp.float32), num_groups)
    qmax = float(2 ** (num_bits - 1) - 1)

    if symmetric:
        scale = jnp.max(jnp.abs(g), axis=-1, keepdims=True) / qmax
        scale = jnp.maximum(scale, 1e-12)
        scaled = g / scale
        zero_point = None
    else:
        lo = jnp.min(g, axis=-1, keepdims=True)
        hi = jnp.max(g, axis=-1, keepdims=True)
        scale = jnp.maximum((hi - lo) / (2 ** num_bits - 1), 1e-12)
        zero_point = lo
        scaled = (g - lo) / scale - qmax - 1

    if stochastic:
        assert rng is not None, "stochastic rounding needs an rng key"
        noise = jax.random.uniform(rng, scaled.shape, minval=-0.5, maxval=0.5)
        q = jnp.floor(scaled + 0.5 + noise)
    else:
        q = jnp.round(scaled)
    q = jnp.clip(q, -qmax - 1, qmax).astype(jnp.int8 if num_bits <= 8
                                            else jnp.int32)
    q = q.reshape(orig_shape)
    return q, scale[:, 0], (zero_point[:, 0] if zero_point is not None
                            else None)


def dequantize(
    q: jnp.ndarray,
    scale: jnp.ndarray,
    zero_point: Optional[jnp.ndarray] = None,
    num_bits: int = 8,
    dtype=jnp.float32,
) -> jnp.ndarray:
    """Inverse of :func:`quantize` (reference dequantize.cu)."""
    orig_shape = q.shape
    num_groups = scale.shape[0]
    g = _grouped(q.astype(jnp.float32), num_groups)
    qmax = float(2 ** (num_bits - 1) - 1)
    if zero_point is None:
        out = g * scale[:, None]
    else:
        out = (g + qmax + 1) * scale[:, None] + zero_point[:, None]
    return out.reshape(orig_shape).astype(dtype)


def quantize_blockwise(x: jnp.ndarray, block: int
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 with one f32 scale per contiguous ``block`` elements
    of the trailing axis — the shared format for the compressed wire
    (``comm/compressed.py``) and the int8 KV cache (serving).

    Returns ``(q, scale)``: ``q`` int8 in ``x``'s shape, ``scale`` float32
    shaped ``x.shape[:-1] + (x.shape[-1] // block,)`` so callers can index
    scales alongside the values they describe (e.g. per ``[B, S, H]`` cache
    slot when ``block == head_dim``).
    """
    assert x.shape[-1] % block == 0, (
        f"trailing axis {x.shape[-1]} not divisible by block {block}")
    q, scale, _ = quantize(x, num_bits=8, num_groups=x.size // block,
                           symmetric=True)
    return q, scale.reshape(x.shape[:-1] + (x.shape[-1] // block,))


def dequantize_blockwise(q: jnp.ndarray, scale: jnp.ndarray,
                         dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of :func:`quantize_blockwise` (symmetric, so just a
    broadcast multiply — no zero point)."""
    g = q.reshape(scale.shape + (-1,)).astype(jnp.float32)
    return (g * scale[..., None]).reshape(q.shape).astype(dtype)


def fake_quantize(x, num_bits=8, num_groups=1, symmetric=True,
                  stochastic=False, rng=None):
    """Quantize-dequantize round trip in the input dtype (what MoQ applies to
    weights during training, reference runtime/quantize.py)."""
    q, scale, zp = quantize(x, num_bits, num_groups, symmetric, stochastic,
                            rng)
    return dequantize(q, scale, zp, num_bits, dtype=x.dtype)


def quantize_weight_per_column(w: jnp.ndarray, num_bits: int = 8
                               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-output-column int quantization of a [in, out] weight —
    the layout :func:`int8_matmul` consumes. (:func:`quantize`'s groups span
    contiguous flattened chunks, i.e. ROW blocks of a 2-D weight, which is
    the wrong axis for a matmul epilogue.)"""
    assert w.ndim == 2, "per-column quantization expects a [in, out] matrix"
    qmax = float(2 ** (num_bits - 1) - 1)
    scale = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0) / qmax  # [out]
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[None, :]),
                 -qmax - 1, qmax).astype(jnp.int8 if num_bits <= 8
                                         else jnp.int32)
    return q, scale


def quantize_weight_per_column_np(w, num_bits: int = 8):
    """HOST-side (numpy) twin of :func:`quantize_weight_per_column` —
    same scale/clip math, kept adjacent so the formulas cannot drift.
    Used when quantizing imported weights before device placement (an
    on-device quantize would land the full-precision leaf on one chip
    first). Also accepts a scan-stacked [L, in, out] weight (per-layer
    per-column scales, shape [L, out])."""
    import numpy as np

    w = np.asarray(w, np.float32)
    assert w.ndim in (2, 3), "expected [in, out] or [L, in, out]"
    qmax = float(2 ** (num_bits - 1) - 1)
    axis = 0 if w.ndim == 2 else 1
    scale = np.maximum(np.abs(w).max(axis=axis) / qmax, 1e-12)
    sb = scale[None, :] if w.ndim == 2 else scale[:, None, :]
    q = np.clip(np.round(w / sb), -qmax - 1, qmax)
    return (q.astype(np.int8 if num_bits <= 8 else np.int32),
            scale.astype(np.float32))


def int8_matmul(x: jnp.ndarray, w_q: jnp.ndarray, w_scale: jnp.ndarray,
                preferred_dtype=jnp.bfloat16) -> jnp.ndarray:
    """Matmul against a per-output-column int8 weight (inference int8 path,
    reference pt_binding int8 GEMM variants): dequantize rides the MXU
    epilogue via scale multiply after an int8->bf16 cast. Quantize the
    weight with :func:`quantize_weight_per_column`."""
    if not (w_scale.ndim == 1 and w_scale.shape[0] == w_q.shape[-1]):
        raise ValueError(
            "int8_matmul needs per-output-column scales: w_scale shape "
            f"{w_scale.shape} does not match weight columns {w_q.shape[-1]} "
            "(use quantize_weight_per_column)"
        )
    w = w_q.astype(preferred_dtype)
    y = jnp.dot(x.astype(preferred_dtype), w,
                preferred_element_type=jnp.float32)
    y = y * w_scale[None, :]
    return y.astype(preferred_dtype)

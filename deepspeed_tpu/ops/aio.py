"""Async host file I/O (reference ``csrc/aio`` + ``ops/aio``): the swap
backend for ZeRO-Infinity-style SSD tiers. ``AioHandle`` mirrors the
reference aio_handle verbs (async_pread/async_pwrite/wait + sync forms)
over the native threadpool, with a synchronous numpy fallback."""

import ctypes
import os
from typing import Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger


class AioHandle:
    def __init__(self, num_threads: int = 4):
        self.num_threads = num_threads
        self._lib = None
        self._h = None
        try:
            from deepspeed_tpu.ops.native.builder import load_library

            self._lib = load_library()
            self._h = self._lib.ds_aio_handle_create(num_threads)
        except Exception as e:  # pragma: no cover - build env dependent
            logger.warning(f"native aio unavailable ({e}); synchronous "
                           f"fallback")

    def close(self):
        if self._lib is not None and self._h:
            self._lib.ds_aio_handle_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass

    # ------------------------------------------------------------------
    def async_pwrite(self, array: np.ndarray, path: str,
                     offset: int = 0) -> None:
        """Queue a write of ``array``'s bytes to ``path`` at ``offset``."""
        buf = np.ascontiguousarray(array)
        if self._h:
            # keep a ref until wait() so the buffer can't be collected
            self._pending = getattr(self, "_pending", [])
            self._pending.append(buf)
            self._lib.ds_aio_pwrite(
                self._h, path.encode(), ctypes.c_void_p(buf.ctypes.data),
                buf.nbytes, offset)
        else:
            with open(path, "r+b" if os.path.exists(path) else "wb") as f:
                f.seek(offset)
                f.write(buf.tobytes())

    def async_pread(self, array: np.ndarray, path: str,
                    offset: int = 0) -> None:
        """Queue a read of ``array.nbytes`` from ``path`` into ``array``."""
        if not array.flags.c_contiguous:
            raise ValueError("read target must be contiguous")
        if self._h:
            self._pending = getattr(self, "_pending", [])
            self._pending.append(array)
            self._lib.ds_aio_pread(
                self._h, path.encode(), ctypes.c_void_p(array.ctypes.data),
                array.nbytes, offset)
        else:
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read(array.nbytes)
            array[...] = np.frombuffer(
                data, dtype=array.dtype).reshape(array.shape)

    def wait(self) -> int:
        """Block until all queued ops finish; raises on I/O error."""
        if self._h:
            err = self._lib.ds_aio_wait(self._h)
            self._pending = []
            if err:
                raise IOError(f"aio error code {err}")
        return 0

    # sync conveniences (reference sync_pread/sync_pwrite)
    def sync_pwrite(self, array: np.ndarray, path: str, offset: int = 0):
        self.async_pwrite(array, path, offset)
        self.wait()

    def sync_pread(self, array: np.ndarray, path: str, offset: int = 0):
        self.async_pread(array, path, offset)
        self.wait()

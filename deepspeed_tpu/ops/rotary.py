"""Rotary position embeddings.

Parity with reference ``csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu``
(exposed as ``apply_rotary_pos_emb`` in pt_binding.cpp): rotate q/k pairs by
position-dependent angles. Pure jnp — XLA fuses the sin/cos/interleave into
the surrounding attention matmuls; the CUDA kernel exists because torch
eager could not.
"""

from typing import Optional, Tuple

import jax.numpy as jnp


def rotary_angles(positions: jnp.ndarray, dim: int, base: float = 10000.0,
                  dtype=jnp.float32) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(cos, sin) tables of shape [..., dim/2] for integer positions."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    angles = positions[..., None].astype(jnp.float32) * inv_freq
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rotary_pos_emb(
    x: jnp.ndarray,
    positions: Optional[jnp.ndarray] = None,
    base: float = 10000.0,
    rotary_dim: Optional[int] = None,
    interleaved: bool = False,
) -> jnp.ndarray:
    """Rotate ``x: [batch, seq, heads, head_dim]``.

    ``interleaved=False``: pairwise half-dim split — the GPT-NeoX/LLaMA
    convention the reference's kernel implements with rotate_half.
    ``interleaved=True``: even/odd pairing — the GPT-J convention (the
    reference kernel's ``rotate_every_two`` variant).
    """
    b, t, h, d = x.shape
    rd = rotary_dim or d
    if positions is None:
        positions = jnp.arange(t)[None, :]
    cos, sin = rotary_angles(positions, rd, base, dtype=x.dtype)
    cos = cos[:, :, None, :]  # [b, t, 1, rd/2]
    sin = sin[:, :, None, :]

    x_rot, x_pass = x[..., :rd], x[..., rd:]
    if interleaved:
        x1, x2 = x_rot[..., ::2], x_rot[..., 1::2]
        rotated = jnp.stack(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
        ).reshape(x_rot.shape)
    else:
        x1, x2 = x_rot[..., : rd // 2], x_rot[..., rd // 2:]
        rotated = jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    if rd < d:
        return jnp.concatenate([rotated, x_pass], axis=-1)
    return rotated

"""Memory-lean softmax cross entropy for large vocabularies.

TPU analogue of the reference's fused loss kernels (the CUDA inference/
training softmax kernels in ``csrc/transformer/softmax_kernels.cu`` fold the
normalization into one pass): at GPT-2 vocab size the logits tensor is by far
the largest activation, so the win is dtype + buffer discipline rather than a
hand-written kernel — XLA fuses the elementwise math into the reductions.

Contract: logits arrive in the compute dtype (bf16). All reductions
(logsumexp, target gather) upcast to f32 *inside the fusion*, so no f32 copy
of the full [tokens, vocab] array is ever materialized; the backward emits
the (softmax - onehot) cotangent directly in the compute dtype, which keeps
the two vocab-size matmuls behind it (dx = dl @ W, dW = x^T @ dl) on the
MXU's bf16 fast path.

Numerics: the logsumexp and the softmax in the backward are exact f32; the
only precision loss vs an all-f32 implementation is the bf16 rounding of the
input logits themselves and of the emitted cotangent (~2^-8 relative), the
standard trade every bf16 training stack makes.
"""

import functools

import jax
import jax.numpy as jnp


def _ce_fwd_math(logits, targets):
    """Per-token nll from [N, V] logits (any float dtype) + [N] targets.

    The f32 upcast must have exactly one consumer chain (the reductions):
    gathering from an f32 view as well makes XLA materialize a full f32
    copy of the logits. Gather from the original dtype and upcast the [N]
    result instead.
    """
    lf = logits.astype(jnp.float32)
    m = jax.lax.stop_gradient(jnp.max(lf, axis=-1, keepdims=True))
    lse = jnp.log(jnp.sum(jnp.exp(lf - m), axis=-1)) + m[..., 0]
    tgt = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0].astype(jnp.float32)
    return lse - tgt, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def softmax_cross_entropy(logits, targets, weights):
    """Weighted mean nll over tokens.

    logits: [N, V] compute dtype; targets: [N] int; weights: [N] f32
    (0/1 mask already folded in, sums to the normalizer's numerator).
    """
    nll, _ = _ce_fwd_math(logits, targets)
    denom = jnp.maximum(jnp.sum(weights), 1.0)
    return jnp.sum(nll * weights) / denom


def _ce_vjp_fwd(logits, targets, weights):
    nll, lse = _ce_fwd_math(logits, targets)
    denom = jnp.maximum(jnp.sum(weights), 1.0)
    loss = jnp.sum(nll * weights) / denom
    return loss, (logits, targets, weights, lse, denom)


def _ce_vjp_bwd(res, g):
    logits, targets, weights, lse, denom = res
    # p - onehot, scaled per-token, emitted in the logits dtype so the
    # consuming matmuls stay bf16
    scale = (g * weights / denom).astype(jnp.float32)[..., None]
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    # one_hot stays an unmaterialized iota-compare inside the fusion (a
    # scatter formulation is ~10x slower on TPU)
    onehot = jax.nn.one_hot(targets, logits.shape[-1], dtype=jnp.float32)
    dlogits = (scale * (p - onehot)).astype(logits.dtype)
    return dlogits, None, None


softmax_cross_entropy.defvjp(_ce_vjp_fwd, _ce_vjp_bwd)


# ---------------------------------------------------------------------------
# Fused LM-head + cross entropy (never materializes [N, V] logits)
# ---------------------------------------------------------------------------
def _n_chunks(n: int, chunk: int) -> int:
    """Chunk count for n tokens (callers pad n to a multiple of chunk;
    the divisor walk is a safety net for direct _flce users)."""
    k = -(-n // max(1, chunk))
    while n % k:
        k += 1
    return k


def _head_logits(x_c, w, bias, vocab_major):
    # [n, E] x [E, V] -> [n, V]   (vocab_major: w is [V, E], tied embedding)
    dims = ((((1,), (1,)) if vocab_major else ((1,), (0,))), ((), ()))
    l = jax.lax.dot_general(x_c, w, dims)
    if bias is not None:
        l = l + bias.astype(l.dtype)
    return l


def fused_linear_cross_entropy(vocab_major, chunk, x, w, bias, targets,
                               weights):
    """Weighted-mean nll of ``softmax(x @ w + bias)`` WITHOUT ever
    materializing the [N, V] logits (at GPT-2 scale the logits + their
    cotangent are the largest activation by far; chunking the token dim
    bounds head memory to [chunk, V] and lets the saved HBM buy a larger
    micro batch or a cheaper remat policy).

    Forward and backward scan over token chunks; the backward recomputes
    each chunk's logits from (x, w) — the same trade ``jax.checkpoint``
    makes, applied to the one matmul whose output dominates memory. Every
    logit value is computed by the identical dot tile as the unfused path,
    so results match ``softmax_cross_entropy`` to bf16 rounding.

    x: [N, E] compute dtype; w: [E, V] ([V, E] when ``vocab_major`` — the
    tied-embedding layout); targets: [N] int; weights: [N] f32 mask.

    N is padded up to a multiple of the chunk (dummy target, zero weight)
    so an awkward token count never degenerates into near-token-count
    scan iterations hunting for a divisor.
    """
    n = x.shape[0]
    c = min(max(1, chunk), n)
    pad = (-n) % c
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        targets = jnp.pad(targets, (0, pad))
        weights = jnp.pad(weights, (0, pad))
    return _flce(vocab_major, c, x, w, bias, targets, weights)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _flce(vocab_major, chunk, x, w, bias, targets, weights):
    loss, _ = _flce_fwd(vocab_major, chunk, x, w, bias, targets, weights)
    return loss


def _flce_fwd(vocab_major, chunk, x, w, bias, targets, weights):
    n, _ = x.shape
    k = _n_chunks(n, chunk)
    xs = x.reshape(k, n // k, -1)
    ts = targets.reshape(k, n // k)
    ws = weights.reshape(k, n // k)

    def body(total, inp):
        x_c, t_c, w_c = inp
        l = _head_logits(x_c, w, bias, vocab_major)
        nll, lse = _ce_fwd_math(l, t_c)
        return total + jnp.sum(nll * w_c), lse

    total, lse = jax.lax.scan(body, jnp.float32(0.0), (xs, ts, ws))
    denom = jnp.maximum(jnp.sum(weights), 1.0)
    return total / denom, (x, w, bias, targets, weights,
                           lse.reshape(n), denom)


def _flce_bwd(vocab_major, chunk, res, g):
    x, w, bias, targets, weights, lse, denom = res
    n, _ = x.shape
    v = w.shape[0] if vocab_major else w.shape[-1]
    k = _n_chunks(n, chunk)
    xs = x.reshape(k, n // k, -1)
    ts = targets.reshape(k, n // k)
    ws = weights.reshape(k, n // k)
    ls = lse.reshape(k, n // k)
    gscale = jnp.asarray(g, jnp.float32) / denom

    dw0 = jnp.zeros(w.shape, jnp.float32)
    db0 = None if bias is None else jnp.zeros(bias.shape, jnp.float32)

    def body(carry, inp):
        dw_acc, db_acc = carry
        x_c, t_c, w_c, lse_c = inp
        l = _head_logits(x_c, w, bias, vocab_major)
        p = jnp.exp(l.astype(jnp.float32) - lse_c[..., None])
        onehot = jax.nn.one_hot(t_c, v, dtype=jnp.float32)
        dl = ((p - onehot) * (w_c * gscale)[..., None]).astype(x_c.dtype)
        if vocab_major:
            # dl [n, V], w [V, E] -> dx [n, E];  dw [V, E] = dl^T @ x_c
            dx_c = jax.lax.dot_general(dl, w, (((1,), (0,)), ((), ())))
            dw_c = jax.lax.dot_general(
                dl, x_c, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            # dl [n, V], w [E, V] -> dx [n, E];  dw [E, V] = x_c^T @ dl
            dx_c = jax.lax.dot_general(dl, w, (((1,), (1,)), ((), ())))
            dw_c = jax.lax.dot_general(
                x_c, dl, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        db_c = None if db_acc is None else db_acc + jnp.sum(
            dl.astype(jnp.float32), axis=0)
        return (dw_acc + dw_c, db_c), dx_c

    (dw, db), dxs = jax.lax.scan(body, (dw0, db0), (xs, ts, ws, ls))
    dx = dxs.reshape(x.shape)
    return (dx, dw.astype(w.dtype),
            None if bias is None else db.astype(bias.dtype), None, None)


_flce.defvjp(_flce_fwd, _flce_bwd)

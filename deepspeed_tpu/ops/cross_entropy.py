"""Memory-lean softmax cross entropy for large vocabularies.

TPU analogue of the reference's fused loss kernels (the CUDA inference/
training softmax kernels in ``csrc/transformer/softmax_kernels.cu`` fold the
normalization into one pass): at GPT-2 vocab size the logits tensor is by far
the largest activation, so the win is dtype + buffer discipline rather than a
hand-written kernel — XLA fuses the elementwise math into the reductions.

Contract: logits arrive in the compute dtype (bf16). All reductions
(logsumexp, target gather) upcast to f32 *inside the fusion*, so no f32 copy
of the full [tokens, vocab] array is ever materialized; the backward emits
the (softmax - onehot) cotangent directly in the compute dtype, which keeps
the two vocab-size matmuls behind it (dx = dl @ W, dW = x^T @ dl) on the
MXU's bf16 fast path.

Numerics: the logsumexp and the softmax in the backward are exact f32; the
only precision loss vs an all-f32 implementation is the bf16 rounding of the
input logits themselves and of the emitted cotangent (~2^-8 relative), the
standard trade every bf16 training stack makes.
"""

import functools

import jax
import jax.numpy as jnp


def _ce_fwd_math(logits, targets):
    """Per-token nll from [N, V] logits (any float dtype) + [N] targets.

    The f32 upcast must have exactly one consumer chain (the reductions):
    gathering from an f32 view as well makes XLA materialize a full f32
    copy of the logits. Gather from the original dtype and upcast the [N]
    result instead.
    """
    lf = logits.astype(jnp.float32)
    m = jax.lax.stop_gradient(jnp.max(lf, axis=-1, keepdims=True))
    lse = jnp.log(jnp.sum(jnp.exp(lf - m), axis=-1)) + m[..., 0]
    tgt = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0].astype(jnp.float32)
    return lse - tgt, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def softmax_cross_entropy(logits, targets, weights):
    """Weighted mean nll over tokens.

    logits: [N, V] compute dtype; targets: [N] int; weights: [N] f32
    (0/1 mask already folded in, sums to the normalizer's numerator).
    """
    nll, _ = _ce_fwd_math(logits, targets)
    denom = jnp.maximum(jnp.sum(weights), 1.0)
    return jnp.sum(nll * weights) / denom


def _ce_vjp_fwd(logits, targets, weights):
    nll, lse = _ce_fwd_math(logits, targets)
    denom = jnp.maximum(jnp.sum(weights), 1.0)
    loss = jnp.sum(nll * weights) / denom
    return loss, (logits, targets, weights, lse, denom)


def _ce_vjp_bwd(res, g):
    logits, targets, weights, lse, denom = res
    # p - onehot, scaled per-token, emitted in the logits dtype so the
    # consuming matmuls stay bf16
    scale = (g * weights / denom).astype(jnp.float32)[..., None]
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    # one_hot stays an unmaterialized iota-compare inside the fusion (a
    # scatter formulation is ~10x slower on TPU)
    onehot = jax.nn.one_hot(targets, logits.shape[-1], dtype=jnp.float32)
    dlogits = (scale * (p - onehot)).astype(logits.dtype)
    return dlogits, None, None


softmax_cross_entropy.defvjp(_ce_vjp_fwd, _ce_vjp_bwd)

"""Fused AdamW as a Pallas kernel.

TPU equivalent of the reference's multi-tensor Adam
(``csrc/adam/multi_tensor_adam.cu:163`` via ``FusedAdam``,
``ops/adam/fused_adam.py:15``): one kernel updates param, m and v in place
(input/output aliasing) in a single pass over each flat shard — one HBM
read/write per buffer instead of optax's (already XLA-fused) elementwise
chain. Exposed as an optax GradientTransformation so it slots into the
engine/ZeRO sharding machinery unchanged.
"""

import functools
from typing import NamedTuple

import chex
import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _adamw_kernel(lr_ref, c1_ref, c2_ref, p_ref, g_ref, m_ref, v_ref,
                  po_ref, mo_ref, vo_ref,
                  *, b1, b2, eps, weight_decay):
    lr = lr_ref[0, 0]
    # bias corrections precomputed host-side (Mosaic has no scalar powf)
    c1 = c1_ref[0, 0]
    c2 = c2_ref[0, 0]
    g = g_ref[...].astype(jnp.float32)
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    # bias correction (reference multi_tensor_adam.cu mode=ADAM_MODE_0/1)
    update = (m / c1) / (jnp.sqrt(v / c2) + eps)
    p = p_ref[...].astype(jnp.float32)
    p = p - lr * (update + weight_decay * p)
    po_ref[...] = p.astype(po_ref.dtype)
    mo_ref[...] = m
    vo_ref[...] = v


def fused_adamw_update(p, g, m, v, lr, step, *, b1=0.9, b2=0.999, eps=1e-8,
                       weight_decay=0.0, block_rows: int = 256):
    """Single-buffer fused update; flattens to (rows, 128) lanes for the VPU
    and streams VMEM-sized row blocks over a 1-D grid."""
    shape = p.shape
    n = p.size
    lanes = 128
    rows = max(1, -(-n // lanes))
    block_rows = min(block_rows, rows)
    rows = -(-rows // block_rows) * block_rows  # multiple of block_rows
    pad = rows * lanes - n

    def flat(x, dtype):
        x = x.reshape(-1).astype(dtype)
        if pad:
            x = jnp.pad(x, (0, pad))
        return x.reshape(rows, lanes)

    pf, gf = flat(p, p.dtype), flat(g, jnp.float32)
    mf, vf = flat(m, jnp.float32), flat(v, jnp.float32)
    step_f = jnp.asarray(step, jnp.float32)
    lr_arr = jnp.full((1, 1), lr, jnp.float32)
    c1_arr = jnp.reshape(1.0 - b1 ** step_f, (1, 1))
    c2_arr = jnp.reshape(1.0 - b2 ** step_f, (1, 1))

    from jax.experimental.pallas import tpu as pltpu

    scalar_spec = pl.BlockSpec((1, 1), lambda i: (0, 0),
                               memory_space=pltpu.SMEM)
    buf_spec = pl.BlockSpec((block_rows, lanes), lambda i: (i, 0))

    po, mo, vo = pl.pallas_call(
        functools.partial(_adamw_kernel, b1=b1, b2=b2, eps=eps,
                          weight_decay=weight_decay),
        grid=(rows // block_rows,),
        in_specs=[scalar_spec, scalar_spec, scalar_spec, buf_spec, buf_spec,
                  buf_spec, buf_spec],
        out_specs=[buf_spec, buf_spec, buf_spec],
        out_shape=[
            jax.ShapeDtypeStruct(pf.shape, p.dtype),
            jax.ShapeDtypeStruct(mf.shape, jnp.float32),
            jax.ShapeDtypeStruct(vf.shape, jnp.float32),
        ],
        input_output_aliases={3: 0, 5: 1, 6: 2},
        interpret=_interpret(),
    )(lr_arr, c1_arr, c2_arr, pf, gf, mf, vf)

    def unflat(x, dtype):
        return x.reshape(-1)[:n].reshape(shape).astype(dtype)

    return unflat(po, p.dtype), unflat(mo, jnp.float32), unflat(vo, jnp.float32)


class FusedAdamWState(NamedTuple):
    count: chex.Array
    mu: optax.Updates
    nu: optax.Updates


def fused_adamw(learning_rate, b1=0.9, b2=0.999, eps=1e-8,
                weight_decay=0.0) -> optax.GradientTransformation:
    """optax wrapper around the Pallas kernel (state layout mirrors
    optax.adamw so ZeRO opt-state sharding rules apply unchanged)."""

    def init(params):
        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return FusedAdamWState(
            count=jnp.zeros([], jnp.int32),
            mu=zeros,
            nu=jax.tree.map(jnp.copy, zeros),
        )

    def update(grads, state, params):
        if params is None:
            raise ValueError("fused_adamw requires params")
        # lr schedule is evaluated at the PRE-increment count (optax
        # convention: first update sees fn(0)); bias correction uses the
        # 1-indexed step like optax/reference Adam
        lr = (learning_rate(state.count) if callable(learning_rate)
              else learning_rate)
        count = state.count + 1
        step = count.astype(jnp.float32)

        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        new_p, new_m, new_v = [], [], []
        for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            pn, mn, vn = fused_adamw_update(
                p, g, m, v, lr, step, b1=b1, b2=b2, eps=eps,
                weight_decay=weight_decay,
            )
            new_p.append(pn)
            new_m.append(mn)
            new_v.append(vn)

        updates = jax.tree.unflatten(
            treedef, [pn - p for pn, p in zip(new_p, flat_p)]
        )
        new_state = FusedAdamWState(
            count=count,
            mu=jax.tree.unflatten(treedef, new_m),
            nu=jax.tree.unflatten(treedef, new_v),
        )
        return updates, new_state

    return optax.GradientTransformation(init, update)

"""Shared constants/helpers for the Pallas kernel library."""

import jax

NEG_INF = -1e30
# logsumexp rows carry 8 broadcast sublane copies to satisfy TPU tiling
LSE_LANES = 8


def interpret() -> bool:
    """Run kernels in interpreter mode off-TPU so the CPU test mesh
    exercises the same code path."""
    return jax.default_backend() != "tpu"


def largest_divisor_block(t: int, want: int = 128) -> int:
    """Largest block size <= want dividing t.

    Shape-blind FALLBACK: kernels that care about the (seq, head_dim,
    device) trade-off — flash attention's causal block pruning above all —
    resolve blocks through ``ops/pallas/autotune.get_flash_blocks``
    (pretuned table / disk cache / live benchmark) and only land here when
    nothing better is known for the shape."""
    b = min(want, t)
    while t % b:
        b -= 1
    return b

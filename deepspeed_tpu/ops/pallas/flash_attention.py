"""Flash attention as Pallas TPU kernels.

The TPU equivalent of the reference's fused attention CUDA kernels
(``csrc/transformer/softmax_kernels.cu``, triton ``triton_flash_attn``,
``ops/transformer/inference/triton_ops.py:103``): blockwise online-softmax
attention that never materializes the [T, T] score matrix in HBM.

Layout: q/k/v are ``[batch, seq, heads, head_dim]`` (the model's natural
layout). The kernel grid is (batch*heads, q_blocks); each program streams K/V
blocks from VMEM with running max/sum rescaling. The backward pass is the
standard two-kernel recompute formulation (dq; then dk/dv) using the saved
logsumexp — O(T) memory like the forward.

On non-TPU backends the kernels run in Pallas interpreter mode, so the CPU
test mesh exercises the exact same code path.

Packed sequences (``segment_ids``): when the data pipeline bin-packs
several documents into one row (``deepspeed_tpu/data/packing.py``),
attention must be restricted to *causal AND same-segment* for the packed
loss to be exact vs running each document alone (docs/data.md). The
segment mask rides into the kernels in two pre-broadcast layouts chosen
to match TPU tiling with no in-kernel transpose:

* ``seg_r [bh, t, LSE_LANES]`` — row layout, sliced like q/lse blocks to
  give the query-side segment id column;
* ``seg_c [bh, LSE_LANES, t]`` — column layout, sliced along the lane
  axis to give the key-side segment id row.

Masking uses the same finite ``NEG_INF`` as the causal path: a masked
score contributes ``exp(-1e30) == 0.0`` exactly to both softmax and its
gradient, so cross-segment leakage is zero, and pad rows (segment 0)
still see their own diagonal so no row is ever fully masked.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from deepspeed_tpu.ops.pallas.common import (
    LSE_LANES,
    NEG_INF,
    interpret as _interpret,
    largest_divisor_block as _block,
)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, block_k,
                has_seg=False):
    if has_seg:
        sq_ref, sk_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    bq, d = q_ref.shape
    t = k_ref.shape[0]
    nk = t // block_k
    qi = pl.program_id(1)

    # keep MXU operands in the input dtype (bf16): f32xf32 dots fall off the
    # systolic array's fast path; accumulate in f32 via preferred_element_type
    q = q_ref[...]  # [bq, d]
    m = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
    if has_seg:
        q_seg = sq_ref[...][:, :1]  # [bq, 1]
        k_seg_row = sk_ref[...]     # [LSE_LANES, t]

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, block_k]
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if has_seg:
            k_seg = jax.lax.dynamic_slice(
                k_seg_row, (0, j * block_k), (1, block_k))
            s = jnp.where(q_seg == k_seg, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if causal:
        # only blocks with k_start <= q_end contribute
        nk_eff = jnp.minimum((qi * bq + bq + block_k - 1) // block_k, nk)
    else:
        nk_eff = nk
    m, l, acc = jax.lax.fori_loop(0, nk_eff, body, (m, l, acc))

    o_ref[...] = (acc / l).astype(o_ref.dtype)
    # lse carries 8 broadcast sublane copies to satisfy TPU tiling
    lse_ref[...] = jnp.broadcast_to(m + jnp.log(l), (bq, LSE_LANES))


def _fwd(q, k, v, seg, scale, causal, block_q, block_k):
    b, t, h, d = q.shape
    bh = b * h
    qf = q.transpose(0, 2, 1, 3).reshape(bh, t, d)
    kf = k.transpose(0, 2, 1, 3).reshape(bh, t, d)
    vf = v.transpose(0, 2, 1, 3).reshape(bh, t, d)
    nq = t // block_q

    in_specs = [
        pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
    ]
    operands = [qf, kf, vf]
    if seg is not None:
        seg_r, seg_c = seg
        in_specs += [
            pl.BlockSpec((None, block_q, LSE_LANES), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, LSE_LANES, t), lambda i, j: (i, 0, 0)),
        ]
        operands += [seg_r, seg_c]

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_k=block_k, has_seg=seg is not None),
        grid=(bh, nq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, LSE_LANES), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, LSE_LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    return o, lse


# ---------------------------------------------------------------------------
# backward (recompute with saved lse)
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                   scale, causal, block_k, has_seg=False):
    if has_seg:
        sq_ref, sk_ref, dq_ref = rest
    else:
        (dq_ref,) = rest
    bq, d = q_ref.shape
    t = k_ref.shape[0]
    nk = t // block_k
    qi = pl.program_id(1)

    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[...][:, :1]
    delta = delta_ref[...][:, :1]
    dq = jnp.zeros((bq, d), jnp.float32)
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
    if has_seg:
        q_seg = sq_ref[...][:, :1]  # [bq, 1]
        k_seg_row = sk_ref[...]     # [LSE_LANES, t]

    def body(j, dq):
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if has_seg:
            k_seg = jax.lax.dynamic_slice(
                k_seg_row, (0, j * block_k), (1, block_k))
            s = jnp.where(q_seg == k_seg, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k_blk.dtype)
        return dq + scale * jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    nk_eff = (jnp.minimum((qi * bq + bq + block_k - 1) // block_k, nk)
              if causal else nk)
    dq = jax.lax.fori_loop(0, nk_eff, body, dq)
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                    scale, causal, block_q, has_seg=False):
    if has_seg:
        sr_ref, sc_ref, dk_ref, dv_ref = rest
    else:
        dk_ref, dv_ref = rest
    bk, d = k_ref.shape
    t = q_ref.shape[0]
    nq = t // block_q
    ki = pl.program_id(1)

    k = k_ref[...]
    v = v_ref[...]
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)
    k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
    if has_seg:
        k_seg = sc_ref[...][:1, :]  # [1, bk]

    def body(i, carry):
        dk, dv = carry
        j = i + (ki * bk) // block_q if causal else i
        q_blk = q_ref[pl.ds(j * block_q, block_q), :]
        do_blk = do_ref[pl.ds(j * block_q, block_q), :]
        lse_blk = lse_ref[pl.ds(j * block_q, block_q), :1]
        delta_blk = delta_ref[pl.ds(j * block_q, block_q), :1]
        s = scale * jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [block_q, bk]
        if causal:
            q_pos = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if has_seg:
            q_seg_blk = sr_ref[pl.ds(j * block_q, block_q), :1]  # [block_q, 1]
            s = jnp.where(q_seg_blk == k_seg, s, NEG_INF)
        p = jnp.exp(s - lse_blk)
        pb = p.astype(do_blk.dtype)
        dv = dv + jax.lax.dot_general(
            pb, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_blk)).astype(q_blk.dtype)
        dk = dk + scale * jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # q blocks entirely before this k block's diagonal contribute nothing
        n_eff = nq - (ki * bk) // block_q
    else:
        n_eff = nq
    dk, dv = jax.lax.fori_loop(0, n_eff, body, (dk, dv))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _bwd_impl(scale, causal, block_q, block_k, q, k, v, o, lse, do,
              seg=None):
    b, t, h, d = q.shape
    bh = b * h

    def flat(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, t, d)

    qf, kf, vf = map(flat, (q, k, v))
    of, dof = o, do  # already [bh, t, d] (the op's internal layout)
    delta = jnp.sum(of.astype(jnp.float32) * dof.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], delta.shape + (LSE_LANES,))

    nq, nk = t // block_q, t // block_k
    dq_in_specs = [
        pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((None, block_q, LSE_LANES), lambda i, j: (i, j, 0)),
        pl.BlockSpec((None, block_q, LSE_LANES), lambda i, j: (i, j, 0)),
    ]
    dq_operands = [qf, kf, vf, dof, lse, delta]
    dkv_in_specs = [
        pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((None, t, LSE_LANES), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((None, t, LSE_LANES), lambda i, j: (i, 0, 0)),
    ]
    dkv_operands = [qf, kf, vf, dof, lse, delta]
    if seg is not None:
        seg_r, seg_c = seg
        dq_in_specs += [
            pl.BlockSpec((None, block_q, LSE_LANES), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, LSE_LANES, t), lambda i, j: (i, 0, 0)),
        ]
        dq_operands += [seg_r, seg_c]
        # dkv slices the row layout by q block in-kernel and takes its own
        # k block from the column layout
        dkv_in_specs += [
            pl.BlockSpec((None, t, LSE_LANES), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, LSE_LANES, block_k), lambda i, j: (i, 0, j)),
        ]
        dkv_operands += [seg_r, seg_c]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, has_seg=seg is not None),
        grid=(bh, nq),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        interpret=_interpret(),
    )(*dq_operands)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, has_seg=seg is not None),
        grid=(bh, nk),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        ],
        interpret=_interpret(),
    )(*dkv_operands)

    def unflat(x):
        return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    return unflat(dq), unflat(dk), unflat(dv)


def _bwd(scale, causal, block_q, block_k, res, g):
    q, k, v, o, lse = res
    return _bwd_impl(scale, causal, block_q, block_k, q, k, v, o, lse, g)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    o, _ = _fwd(q, k, v, None, scale, causal, block_q, block_k)
    return o


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    from jax.ad_checkpoint import checkpoint_name

    o, lse = _fwd(q, k, v, None, scale, causal, block_q, block_k)
    # under remat, tagging the kernel outputs lets a names-aware policy keep
    # them (o: 2 bytes/elem, lse: 1/head_dim of that) instead of re-running
    # the whole forward kernel to regenerate residuals in the backward pass
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return o, (q, k, v, o, lse)


_flash.defvjp(_flash_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_seg(q, k, v, seg_r, seg_c, scale, causal, block_q, block_k):
    o, _ = _fwd(q, k, v, (seg_r, seg_c), scale, causal, block_q, block_k)
    return o


def _flash_seg_fwd(q, k, v, seg_r, seg_c, scale, causal, block_q, block_k):
    from jax.ad_checkpoint import checkpoint_name

    o, lse = _fwd(q, k, v, (seg_r, seg_c), scale, causal, block_q, block_k)
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return o, (q, k, v, seg_r, seg_c, o, lse)


def _flash_seg_bwd(scale, causal, block_q, block_k, res, g):
    q, k, v, seg_r, seg_c, o, lse = res
    dq, dk, dv = _bwd_impl(scale, causal, block_q, block_k, q, k, v, o, lse,
                           g, seg=(seg_r, seg_c))
    # integer operands take symbolic-zero (float0) cotangents
    dseg_r = np.zeros(seg_r.shape, jax.dtypes.float0)
    dseg_c = np.zeros(seg_c.shape, jax.dtypes.float0)
    return dq, dk, dv, dseg_r, dseg_c


_flash_seg.defvjp(_flash_seg_fwd, _flash_seg_bwd)


def flash_attention(q, k, v, *, causal: bool = True, scale: float = None,
                    segment_ids=None, block_q: int = None,
                    block_k: int = None, autotune: bool = None):
    """Blockwise attention over ``[batch, seq, heads, head_dim]`` inputs.

    Memory is O(seq) per program instead of O(seq^2); the [T, T] score matrix
    only ever exists one [block_q, block_k] tile at a time in VMEM.

    ``segment_ids`` (``[batch, seq]`` int, 0 = padding) restricts attention
    to *causal AND same-segment* for packed-sequence batches
    (``deepspeed_tpu/data/``): position i attends j iff ``j <= i`` and
    ``seg[i] == seg[j]``, which makes the packed forward/backward exact vs
    per-document unpacked attention (docs/data.md).

    ``block_q``/``block_k`` default to the shape-tuned resolution in
    ``ops/pallas/autotune.py`` (disk cache -> pretuned table -> optional
    live benchmark gated by ``autotune``/``DS_TPU_FLASH_AUTOTUNE`` -> the
    historical want-512 divisor heuristic); pass them explicitly to pin.
    """
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    if block_q is None or block_k is None:
        from deepspeed_tpu.ops.pallas.autotune import get_flash_blocks

        tuned_q, tuned_k = get_flash_blocks(
            t, d, q.dtype, causal, autotune=autotune)
        block_q = tuned_q if block_q is None else block_q
        block_k = tuned_k if block_k is None else block_k
    block_q = _block(t, block_q)
    block_k = _block(t, block_k)
    if segment_ids is None:
        of = _flash(q, k, v, float(scale), bool(causal), block_q, block_k)
    else:
        if segment_ids.shape != (b, t):
            raise ValueError(
                f"segment_ids must be [batch, seq] = {(b, t)}, got "
                f"{segment_ids.shape}")
        # head-replicated [b*h, t] matches the kernels' batch-major
        # flattening (program i = b_idx * h + h_idx)
        segf = jnp.repeat(segment_ids.astype(jnp.int32), h, axis=0)
        seg_r = jnp.broadcast_to(segf[:, :, None], (b * h, t, LSE_LANES))
        seg_c = jnp.broadcast_to(segf[:, None, :], (b * h, LSE_LANES, t))
        of = _flash_seg(q, k, v, seg_r, seg_c, float(scale), bool(causal),
                        block_q, block_k)
    return of.reshape(b, h, t, d).transpose(0, 2, 1, 3)

"""Pallas TPU kernels — the native-kernel layer (reference ``csrc/`` CUDA,
SURVEY.md §2.4). Kernels run compiled on TPU and in interpreter mode on the
CPU test mesh."""

from deepspeed_tpu.ops.pallas.flash_attention import flash_attention  # noqa: F401
from deepspeed_tpu.ops.pallas.fused_adam import (  # noqa: F401
    fused_adamw,
    fused_adamw_update,
)

"""Shape-tuned (block_q, block_k) selection for the flash-attention kernel.

``largest_divisor_block``'s fixed ``want`` heuristic picks the largest
divisor of the sequence length — shape-blind: for CAUSAL attention the
kernel skips fully-masked K blocks (``nk_eff`` pruning in
``flash_attention.py``), so a smaller ``block_k`` does strictly less work
per q-row, while a larger ``block_q`` amortizes grid overhead. The best
trade depends on (seq, head_dim, dtype, device) — exactly what a fixed
default cannot know.

Resolution order for :func:`get_flash_blocks` (first hit wins):

1. in-memory cache (one lookup per process per key)
2. on-disk JSON cache — ``$DS_TPU_PALLAS_CACHE`` or
   ``~/.cache/deepspeed_tpu/flash_blocks.json``, keyed by
   ``device_kind|seq|head_dim|dtype|causal``; written by a previous
   autotune run on this host. A corrupt/unreadable file falls through
   (warn once) and is overwritten by the next tuned write.
3. shipped pretuned table (:data:`PRETUNED`) — seeds for the shapes the
   1.3B benchmark config hits, derived from the kernel's VMEM/pruning
   model (docs/performance.md); refreshed in place by live autotunes.
4. live benchmark at the actual shape, IF enabled (``autotune=True`` or
   ``DS_TPU_FLASH_AUTOTUNE=1``): times the jitted fwd+bwd over a
   divisor-filtered candidate grid and persists the winner to (2).
5. the ``largest_divisor_block`` heuristic — today's default, unchanged.

Every cached/pretuned entry is re-validated against the current shape
(divisibility) before use, so a stale or hand-edited cache can never
produce an invalid launch. Default-safe: with no cache, no pretuned hit,
and autotuning off, behavior is identical to the old fixed default.
"""

import json
import os
import threading
import warnings
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.pallas.common import largest_divisor_block

_CACHE_ENV = "DS_TPU_PALLAS_CACHE"
_AUTOTUNE_ENV = "DS_TPU_FLASH_AUTOTUNE"
_DEFAULT_WANT = 512  # flash_attention's historical fixed block default

# (device_kind, seq, head_dim, dtype, causal) -> (block_q, block_k).
# Seeds for the 1.3B/seq-1024 shape (n_embd=2048 / 16 heads -> d=128):
# causal entries keep block_k at seq/4 so the kernel's nk_eff pruning
# skips ~ the upper-triangle (block_k=seq would always compute the full
# square), and block_q at seq/2 to halve grid launches. A live autotune
# (DS_TPU_FLASH_AUTOTUNE=1) overwrites these via the disk cache.
PRETUNED: Dict[Tuple[str, int, int, str, bool], Tuple[int, int]] = {}
for _kind in ("TPU v4", "TPU v5 lite", "TPU v5e", "TPU v5p", "TPU v6 lite",
              "TPU v6e"):
    for _dt in ("bfloat16", "float32"):
        PRETUNED[(_kind, 1024, 128, _dt, True)] = (512, 256)
        PRETUNED[(_kind, 2048, 128, _dt, True)] = (512, 256)
        # Long-context seeds: past 2048 the inner k loop dominates the
        # grid, so block_k doubles to 512 to halve k iterations (a
        # 512x128 k/v tile is 128 KiB in bf16 — q, k, v, o plus the
        # f32 acc/lse scratch stay well under the ~16 MiB VMEM budget)
        # while block_q holds at 512: q tiles scale launches, not reuse.
        PRETUNED[(_kind, 4096, 128, _dt, True)] = (512, 512)
        PRETUNED[(_kind, 8192, 128, _dt, True)] = (512, 512)

_lock = threading.Lock()
_mem_cache: Dict[str, Tuple[int, int]] = {}
_disk_warned = False


def cache_path() -> str:
    return os.environ.get(_CACHE_ENV) or os.path.join(
        os.path.expanduser("~"), ".cache", "deepspeed_tpu",
        "flash_blocks.json")


def cache_key(device_kind: str, t: int, d: int, dtype, causal: bool) -> str:
    return f"{device_kind}|{int(t)}|{int(d)}|{jnp.dtype(dtype).name}|" \
           f"{bool(causal)}"


def _load_disk_cache() -> Dict[str, List[int]]:
    global _disk_warned
    path = cache_path()
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            raise ValueError(f"expected a JSON object, got {type(data)}")
        return data
    except (OSError, ValueError) as e:
        if not _disk_warned:
            _disk_warned = True
            warnings.warn(
                f"ignoring corrupt Pallas autotune cache {path!r} ({e}); "
                "falling back to the block-size heuristic — the next "
                "autotune run rewrites it", RuntimeWarning)
        return {}


def _store_disk_cache(key: str, blocks: Tuple[int, int]) -> None:
    path = cache_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    data = _load_disk_cache()
    data[key] = [int(blocks[0]), int(blocks[1])]
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def _valid(blocks, t: int) -> Optional[Tuple[int, int]]:
    """Sanity-check a cached/pretuned entry against the current shape."""
    try:
        bq, bk = int(blocks[0]), int(blocks[1])
    except (TypeError, ValueError, IndexError):
        return None
    if bq < 1 or bk < 1 or t % bq or t % bk:
        return None
    return bq, bk


def default_candidates(t: int) -> List[Tuple[int, int]]:
    """Divisor-filtered (block_q, block_k) grid around the MXU-friendly
    power-of-two sizes, bounded so the f32 score tile stays well under a
    VMEM core (block_q*block_k <= 512*1024 -> 2 MB)."""
    sizes = [b for b in (128, 256, 512, 1024) if b <= t and t % b == 0]
    if not sizes:  # short/odd seq: fall back to the divisor heuristic sizes
        sizes = sorted({largest_divisor_block(t, w)
                        for w in (128, 256, 512)})
    return [(bq, bk) for bq in sizes for bk in sizes
            if bq * bk <= 512 * 1024]


def benchmark_candidates(t: int, d: int, dtype, causal: bool,
                         candidates: List[Tuple[int, int]],
                         batch_heads: int = 4, iters: int = 3
                         ) -> Tuple[int, int]:
    """Time the jitted flash fwd+bwd at the actual (seq, head_dim) shape
    for each candidate and return the fastest. One compile + ``iters``
    timed runs per candidate; called once per (shape, device) ever, the
    winner is persisted to the disk cache."""
    import time

    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    shape = (1, t, batch_heads, d)
    q = jnp.asarray(rng.randn(*shape), jnp.dtype(dtype))
    k = jnp.asarray(rng.randn(*shape), jnp.dtype(dtype))
    v = jnp.asarray(rng.randn(*shape), jnp.dtype(dtype))

    best, best_dt = None, float("inf")
    for bq, bk in candidates:

        def loss(q, k, v, bq=bq, bk=bk):
            return jnp.sum(flash_attention(
                q, k, v, causal=causal, block_q=bq, block_k=bk))

        try:
            step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            jax.block_until_ready(step(q, k, v))  # compile + warm
            t0 = time.perf_counter()
            for _ in range(iters):
                jax.block_until_ready(step(q, k, v))
            dt = (time.perf_counter() - t0) / iters
        except Exception as e:  # candidate failed to compile/run: skip it
            warnings.warn(
                f"flash autotune candidate ({bq},{bk}) failed: {e}",
                RuntimeWarning)
            continue
        if dt < best_dt:
            best, best_dt = (bq, bk), dt
    if best is None:
        raise RuntimeError(
            f"flash autotune: no candidate ran for t={t} d={d}")
    return best


def get_flash_blocks(t: int, d: int, dtype, causal: bool, *,
                     want_q: int = _DEFAULT_WANT,
                     want_k: int = _DEFAULT_WANT,
                     autotune: Optional[bool] = None,
                     candidates: Optional[List[Tuple[int, int]]] = None
                     ) -> Tuple[int, int]:
    """Resolve (block_q, block_k) for a flash-attention launch.

    ``autotune=None`` defers to the ``DS_TPU_FLASH_AUTOTUNE`` env flag;
    ``candidates`` overrides the benchmark grid (tests use tiny ones).
    """
    heuristic = (largest_divisor_block(t, want_q),
                 largest_divisor_block(t, want_k))
    try:
        device_kind = jax.devices()[0].device_kind
    except Exception:
        return heuristic
    key = cache_key(device_kind, t, d, dtype, causal)

    with _lock:
        hit = _mem_cache.get(key)
        if hit is not None:
            return hit
        entry = _valid(_load_disk_cache().get(key), t)
        if entry is not None:
            _mem_cache[key] = entry
            return entry
        pre = _valid(PRETUNED.get(
            (device_kind, int(t), int(d), jnp.dtype(dtype).name,
             bool(causal))), t)
        if pre is not None:
            _mem_cache[key] = pre
            return pre

    if autotune is None:
        autotune = os.environ.get(_AUTOTUNE_ENV, "0") not in ("", "0")
    if not autotune:
        return heuristic

    tuned = benchmark_candidates(
        t, d, dtype, causal, candidates or default_candidates(t))
    with _lock:
        _mem_cache[key] = tuned
        try:
            _store_disk_cache(key, tuned)
        except OSError as e:
            warnings.warn(
                f"flash autotune: could not persist winner to "
                f"{cache_path()!r} ({e}); it stays in-memory for this "
                "process", RuntimeWarning)
    return tuned


def clear_memory_cache() -> None:
    """Test hook: drop the per-process memoization (disk cache untouched)."""
    global _disk_warned
    with _lock:
        _mem_cache.clear()
        _disk_warned = False

"""Elasticity config (reference ``deepspeed/elasticity/config.py``).

Keys keep the reference names (``min_gpus``/``max_gpus``/
``num_gpus_per_node``) so existing configs parse unchanged; on TPU they
count chips and chips-per-host. ``min_chips``/``max_chips``/
``num_chips_per_host`` are accepted as aliases.
"""

from typing import Any, Dict


class ElasticityError(Exception):
    """Base error for elasticity module."""


class ElasticityConfigError(ElasticityError):
    """Elasticity configuration error."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """World size incompatible with the given elastic config."""


LATEST_ELASTICITY_VERSION = 0.2
DEEPSPEED_ELASTICITY_CONFIG = "DEEPSPEED_ELASTICITY_CONFIG"


class ElasticityConfig:
    """Parsed elasticity block::

        "elasticity": {
            "enabled": true,
            "max_train_batch_size": 2000,
            "micro_batch_sizes": [2, 4, 6],
            "min_gpus": 1,
            "max_gpus": 10000,
            "min_time": 20,
            "version": 0.2,
            "ignore_non_elastic_batch_info": false,
            "prefer_larger_batch": true,
            "model_parallel_size": 1,
            "num_gpus_per_node": 1
        }
    """

    def __init__(self, param_dict: Dict[str, Any]):
        self.enabled = param_dict.get("enabled", False)
        if self.enabled:
            if "max_train_batch_size" not in param_dict:
                raise ElasticityConfigError(
                    "Elasticity config missing max_train_batch_size")
            if "micro_batch_sizes" not in param_dict:
                raise ElasticityConfigError(
                    "Elasticity config missing micro_batch_sizes")
        self.max_acceptable_batch_size = param_dict.get(
            "max_train_batch_size", 2000)
        self.micro_batches = param_dict.get("micro_batch_sizes", [2, 4, 6])

        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                f"micro_batch_sizes must be a list, got "
                f"{type(self.micro_batches).__name__}")
        if not all(isinstance(m, int) and m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"micro_batch_sizes must be positive ints, got "
                f"{self.micro_batches}")

        self.min_gpus = param_dict.get(
            "min_chips", param_dict.get("min_gpus", 1))
        self.max_gpus = param_dict.get(
            "max_chips", param_dict.get("max_gpus", 10000))
        if self.min_gpus < 1 or self.max_gpus < 1:
            raise ElasticityConfigError("min/max chip counts must be >= 1")
        if self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                f"max ({self.max_gpus}) < min ({self.min_gpus}) chip count")

        self.model_parallel_size = param_dict.get("model_parallel_size", 1)
        self.num_gpus_per_node = param_dict.get(
            "num_chips_per_host", param_dict.get("num_gpus_per_node", 1))
        if self.model_parallel_size < 1 or self.num_gpus_per_node < 1:
            raise ElasticityConfigError(
                "model_parallel_size and chips-per-host must be >= 1")

        self.min_time = param_dict.get("min_time", 0)
        self.version = param_dict.get("version", 0.2)
        self.prefer_larger_batch_size = param_dict.get(
            "prefer_larger_batch", True)
        self.ignore_non_elastic_batch_info = param_dict.get(
            "ignore_non_elastic_batch_info", False)

    def repr_dict(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "max_train_batch_size": self.max_acceptable_batch_size,
            "micro_batch_sizes": self.micro_batches,
            "min_gpus": self.min_gpus,
            "max_gpus": self.max_gpus,
            "version": self.version,
        }

"""Batch-size elasticity solver (reference ``elasticity/elasticity.py:125-380``).

Picks one global train batch size <= the user's maximum that is compatible
with the largest possible set of chip counts, so a job can restart at a
different world size (slice resize, preemption) with the *identical*
effective batch — convergence-neutral elasticity via gradient accumulation:
``batch = micro_batch * grad_accum * dp_world``.

The math is hardware-agnostic; v0.2 adds host granularity (chips-per-host)
and model parallelism, where the schedulable unit is a host and the data-
parallel world is ``chips / model_parallel_size``.
"""

import json
import math
import os
from typing import List, Optional, Tuple

import numpy as np

from deepspeed_tpu.elasticity.config import (
    DEEPSPEED_ELASTICITY_CONFIG,
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    LATEST_ELASTICITY_VERSION,
)
from deepspeed_tpu.utils.logging import logger

_HCN_CACHE: List[int] = []


def highly_composite_numbers(limit: int) -> List[int]:
    """Record-setting divisor counts up to ``limit`` (computed via divisor
    sieve, cached). These make the best batch multipliers: maximally many
    chip counts divide them."""
    global _HCN_CACHE
    if _HCN_CACHE and _HCN_CACHE[-1] >= limit:
        return [h for h in _HCN_CACHE if h <= limit]
    limit = max(limit, 1)
    counts = np.zeros(limit + 1, dtype=np.int32)
    for d in range(1, limit + 1):
        counts[d::d] += 1
    hcns, best = [], 0
    for n in range(1, limit + 1):
        if counts[n] > best:
            hcns.append(n)
            best = counts[n]
    _HCN_CACHE = hcns
    return hcns


def get_candidate_batch_sizes(base_list: List[int],
                              max_acceptable_batch_size: int) -> List[int]:
    """For each base (micro-batch or their lcm), the largest
    highly-composite multiple of it within the cap."""
    candidates = set()
    for base in base_list:
        if base >= max_acceptable_batch_size:
            candidates.add(base)
            continue
        hcns = highly_composite_numbers(max_acceptable_batch_size // base)
        candidates.add(hcns[-1] * base)
    return sorted(candidates)


def get_valid_gpus(batch_size: int, micro_batches: List[int],
                   min_valid_gpus: int, max_valid_gpus: int) -> List[int]:
    """Chip counts g such that some micro-batch evenly decomposes
    ``batch_size = mb * gas * g``."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb:
            continue
        per_mb = batch_size // mb
        # g must divide batch/mb
        for g in range(1, int(math.isqrt(per_mb)) + 1):
            if per_mb % g == 0:
                for cand in (g, per_mb // g):
                    if min_valid_gpus <= cand <= max_valid_gpus:
                        valid.add(cand)
    return sorted(valid)


def _get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size,
                             min_gpus=1, max_gpus=10000,
                             prefer_larger=True) -> Tuple[int, List[int]]:
    lcm = int(np.lcm.reduce(np.array(micro_batches, dtype=np.int64)))
    base_list = list(micro_batches) + [lcm]
    candidates = get_candidate_batch_sizes(base_list,
                                           max_acceptable_batch_size)
    final_batch, best_gpus = 0, []
    for batch in candidates:
        gpus = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        better = len(gpus) > len(best_gpus) or (
            len(gpus) == len(best_gpus)
            and ((prefer_larger and batch > final_batch)
                 or (not prefer_larger and batch < final_batch)))
        if better:
            final_batch, best_gpus = batch, gpus
    return final_batch, best_gpus


def _get_compatible_gpus_v02(micro_batches, max_acceptable_batch_size,
                             current_num_gpus, min_gpus=1, max_gpus=10000,
                             prefer_larger=True, num_gpus_per_node=1,
                             model_parallel_size=1):
    if num_gpus_per_node % model_parallel_size != 0:
        raise ElasticityError(
            f"chips per host ({num_gpus_per_node}) must be divisible by "
            f"model parallel size ({model_parallel_size})")
    dp_per_node = num_gpus_per_node // model_parallel_size

    def pick_microbatch(batch, dp_world):
        chosen = None
        dp_world = max(dp_world, 1)
        for mb in micro_batches:
            if (batch // dp_world) % mb == 0:
                if chosen is None or (prefer_larger and mb > chosen):
                    chosen = mb
        return chosen

    # schedule at host granularity: solve v0.1 in units of hosts
    batch_per_node, valid_nodes = _get_compatible_gpus_v01(
        micro_batches,
        max(max_acceptable_batch_size // dp_per_node, 1),
        max(min_gpus // num_gpus_per_node, 1),
        max(max_gpus // num_gpus_per_node, 1),
        prefer_larger=prefer_larger)
    final_batch = int(batch_per_node) * dp_per_node
    valid_dp_worlds = [n * dp_per_node for n in valid_nodes]

    if current_num_gpus // model_parallel_size in valid_dp_worlds:
        return final_batch, valid_dp_worlds, pick_microbatch(
            final_batch, current_num_gpus // model_parallel_size)

    # current world not in the envelope: best batch for this exact world
    current_dp = (current_num_gpus // num_gpus_per_node) * dp_per_node
    current_dp = max(current_dp, 1)
    per_mb = [mb * current_dp * (max_acceptable_batch_size
                                 // (mb * current_dp))
              for mb in micro_batches if mb * current_dp
              <= max_acceptable_batch_size]
    if not per_mb:
        raise ElasticityIncompatibleWorldSize(
            f"no micro batch fits world {current_num_gpus} under batch cap "
            f"{max_acceptable_batch_size}")
    batch = max(per_mb) if prefer_larger else min(per_mb)
    # validate the micro batch against the dp world actually returned
    return batch, [current_dp], pick_microbatch(batch, current_dp)


def elasticity_enabled(ds_config: dict) -> bool:
    return ds_config.get("elasticity", {}).get("enabled", False)


def ensure_immutable_elastic_config(runtime_elastic_config_dict: dict):
    """Cross-check the runtime elastic config against the one the resource
    scheduler saw (via env), reference elasticity.py:256."""
    if DEEPSPEED_ELASTICITY_CONFIG not in os.environ:
        logger.warning(
            "DEEPSPEED_ELASTICITY_CONFIG not set; cannot guarantee resource "
            "scheduler uses a compatible chip-count envelope")
        return
    sched = ElasticityConfig(
        json.loads(os.environ[DEEPSPEED_ELASTICITY_CONFIG]))
    run = ElasticityConfig(runtime_elastic_config_dict)
    for attr in ("max_acceptable_batch_size", "micro_batches", "version"):
        if getattr(sched, attr) != getattr(run, attr):
            raise ElasticityConfigError(
                f"elastic config mismatch on {attr}: scheduler "
                f"{getattr(sched, attr)} vs runtime {getattr(run, attr)}")


def compute_elastic_config(ds_config: dict,
                           target_deepspeed_version: Optional[str] = None,
                           world_size: int = 0,
                           return_microbatch: bool = False):
    """Compute (final_batch_size, valid_chip_counts[, micro_batch]).

    Given the elastic envelope config, returns one deterministic global
    batch size and every chip count it can run at. With ``world_size`` (or
    env WORLD_SIZE) also validates the current world and optionally returns
    the micro-batch to use there.
    """
    if not isinstance(ds_config, dict):
        raise ValueError(
            f"expected ds_config dict, got {type(ds_config).__name__}")
    if "elasticity" not in ds_config:
        raise ElasticityConfigError(
            "'elasticity' is missing from the config json")
    elastic_dict = ds_config["elasticity"]
    if not elastic_dict.get("enabled", False):
        raise ElasticityConfigError(
            "elasticity is disabled; set 'enabled': true")
    cfg = ElasticityConfig(elastic_dict)

    if cfg.model_parallel_size > 1 and float(cfg.version) != 0.2:
        raise ElasticityConfigError(
            f"elasticity v{cfg.version} does not support model parallelism")
    if float(cfg.version) > LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"elasticity v{cfg.version} not supported (latest "
            f"{LATEST_ELASTICITY_VERSION})")

    if world_size == 0 and os.getenv("WORLD_SIZE", "").isnumeric():
        world_size = int(os.environ["WORLD_SIZE"])

    micro_batch = None
    if float(cfg.version) == 0.1:
        final_batch, valid_gpus = _get_compatible_gpus_v01(
            cfg.micro_batches, cfg.max_acceptable_batch_size,
            cfg.min_gpus, cfg.max_gpus,
            prefer_larger=cfg.prefer_larger_batch_size)
    elif float(cfg.version) == 0.2:
        if world_size == 0:
            raise ElasticityConfigError(
                "elasticity v0.2 needs the current world size (arg or "
                "WORLD_SIZE env)")
        final_batch, valid_gpus, micro_batch = _get_compatible_gpus_v02(
            cfg.micro_batches, cfg.max_acceptable_batch_size, world_size,
            cfg.min_gpus, cfg.max_gpus,
            prefer_larger=cfg.prefer_larger_batch_size,
            num_gpus_per_node=cfg.num_gpus_per_node,
            model_parallel_size=cfg.model_parallel_size)
    else:
        raise ElasticityConfigError(f"unknown elasticity version "
                                    f"{cfg.version}")

    if world_size > 0 and float(cfg.version) == 0.1:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not in valid chip counts "
                f"{valid_gpus}")
        if return_microbatch:
            for mb in sorted(cfg.micro_batches,
                             reverse=cfg.prefer_larger_batch_size):
                if final_batch % (mb * world_size) == 0:
                    micro_batch = mb
                    break

    logger.info(
        f"elasticity: final_batch_size={final_batch}, "
        f"valid chip counts={valid_gpus}")
    if return_microbatch:
        return final_batch, valid_gpus, micro_batch
    return final_batch, valid_gpus

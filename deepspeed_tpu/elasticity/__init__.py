"""Batch-size elasticity (reference ``deepspeed/elasticity/``): restart a
job at any chip count in a precomputed envelope with the identical global
batch. On TPU this pairs with slice resize/preemption restart; the
torch-elastic agent has no analogue (the launcher re-execs instead)."""

from deepspeed_tpu.elasticity.config import (  # noqa: F401
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
)
from deepspeed_tpu.elasticity.elasticity import (  # noqa: F401
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
    get_candidate_batch_sizes,
    get_valid_gpus,
    highly_composite_numbers,
)

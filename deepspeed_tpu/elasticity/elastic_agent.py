"""Elastic training agent.

Parity with reference ``elasticity/elastic_agent.py:23`` ``DSElasticAgent``:
there, a torch-elastic agent supervises the worker group, re-rendezvouses on
membership change, and restarts workers with updated WORLD_SIZE env. The
TPU re-design supervises ONE process per host around slice preemption:

* restart-on-failure loop with capped retries and backoff (the torch-elastic
  ``monitor`` loop, elastic_agent.py:115);
* on each (re)start the world is re-discovered via a host-count callback
  (slice repair can resize), and the batch config is re-solved with
  ``compute_elastic_config`` so the effective batch stays fixed across
  world-size changes — the reference's core elasticity invariant;
* workers are expected to resume from their latest checkpoint
  (``load_checkpoint(tag='latest')``), which is the reference's recovery
  path too — the agent only guarantees a consistent relaunch env.
"""

import os
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
from deepspeed_tpu.utils.logging import logger


class ElasticAgentError(RuntimeError):
    pass


class DSElasticAgent:
    """Supervise an elastic single-host-group training process.

    Parameters
    ----------
    cmd:
        argv of the training process (the agent prepends nothing; env
        carries the elastic state).
    ds_config:
        DeepSpeed-style config dict with an ``elasticity`` block; used to
        re-solve micro-batch/GAS per world size.
    discover_world:
        callback -> current world size (number of host processes). Defaults
        to the DS_TPU_NUM_PROCS env or 1. In a real deployment this queries
        the TPU slice/pod state after repair.
    max_restarts / backoff_s:
        restart budget for non-zero worker exits (preemption, slice loss).
    """

    def __init__(self, cmd: List[str], ds_config: Dict,
                 discover_world: Optional[Callable[[], int]] = None,
                 max_restarts: int = 3, backoff_s: float = 5.0,
                 env: Optional[Dict[str, str]] = None):
        self.cmd = list(cmd)
        self.ds_config = ds_config
        self.discover_world = discover_world or (
            lambda: int(os.environ.get("DS_TPU_NUM_PROCS", "1")))
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.env = dict(env if env is not None else os.environ)
        self.restart_count = 0
        self._proc: Optional[subprocess.Popen] = None

    # ------------------------------------------------------------------
    def _worker_env(self, world: int) -> Dict[str, str]:
        env = dict(self.env)
        env["DS_TPU_NUM_PROCS"] = str(world)
        env["DS_TPU_ELASTIC_RESTART"] = str(self.restart_count)
        elastic = self.ds_config.get("elasticity")
        if elastic and elastic.get("enabled"):
            # re-solve the batch triad for the new world size so
            # train_batch_size stays inside the elastic envelope
            chips = world * int(env.get("DS_TPU_CHIPS_PER_PROC", "1"))
            final_bs, _valid, micro = compute_elastic_config(
                self.ds_config, world_size=chips, return_microbatch=True)
            # the solver guarantees divisibility by micro * dp_world where
            # dp_world = chips / model_parallel_size (elasticity.py
            # pick_microbatch) — the exported triad must multiply back
            # exactly, else the effective batch silently shrinks and the
            # fixed-batch invariant this agent exists to guarantee breaks
            mp = int(elastic.get("model_parallel_size", 1))
            dp_world = max(1, chips // mp)
            if final_bs % (micro * dp_world):
                raise ElasticAgentError(
                    f"elastic config is inconsistent: batch {final_bs} is "
                    f"not divisible by micro*dp_world ({micro}*{dp_world})")
            gas = final_bs // (micro * dp_world)
            env["DS_TPU_ELASTIC_TRAIN_BATCH"] = str(final_bs)
            env["DS_TPU_ELASTIC_MICRO_BATCH"] = str(micro)
            env["DS_TPU_ELASTIC_GAS"] = str(gas)
            logger.info(
                f"elastic relaunch: world={world} batch={final_bs} "
                f"micro={micro} gas={gas}")
        return env

    def _launch(self) -> subprocess.Popen:
        world = self.discover_world()
        if world < 1:
            raise ElasticAgentError(f"discovered world size {world} < 1")
        return subprocess.Popen(self.cmd, env=self._worker_env(world))

    # ------------------------------------------------------------------
    def run(self) -> int:
        """Supervision loop: returns the final exit code (0 on success)."""
        while True:
            self._proc = self._launch()
            try:
                rc = self._proc.wait()
            except KeyboardInterrupt:
                self._proc.send_signal(signal.SIGTERM)
                self._proc.wait()
                return 1
            if rc == 0:
                return 0
            if self.restart_count >= self.max_restarts:
                logger.error(
                    f"worker failed (rc={rc}) and restart budget "
                    f"({self.max_restarts}) is exhausted")
                return rc
            self.restart_count += 1
            logger.warning(
                f"worker failed (rc={rc}); elastic restart "
                f"{self.restart_count}/{self.max_restarts} in "
                f"{self.backoff_s:.0f}s")
            time.sleep(self.backoff_s)


def main(argv=None) -> int:
    """CLI: ``python -m deepspeed_tpu.elasticity.elastic_agent [--config
    ds_config.json] -- cmd ...``"""
    import argparse
    import json

    p = argparse.ArgumentParser()
    p.add_argument("--config", default=None)
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--backoff", type=float, default=5.0)
    p.add_argument("cmd", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        p.error("no worker command given")
    cfg = {}
    if args.config:
        with open(args.config) as f:
            cfg = json.load(f)
    agent = DSElasticAgent(cmd, cfg, max_restarts=args.max_restarts,
                           backoff_s=args.backoff)
    return agent.run()


if __name__ == "__main__":
    sys.exit(main())

"""Elastic training agent.

Parity with reference ``elasticity/elastic_agent.py:23`` ``DSElasticAgent``:
there, a torch-elastic agent supervises the worker group, re-rendezvouses on
membership change, and restarts workers with updated WORLD_SIZE env. The
TPU re-design supervises ONE process per host around slice preemption:

* restart-on-failure loop with capped retries and backoff (the torch-elastic
  ``monitor`` loop, elastic_agent.py:115);
* on each (re)start the world is re-discovered via a host-count callback
  (slice repair can resize), and the batch config is re-solved with
  ``compute_elastic_config`` so the effective batch stays fixed across
  world-size changes — the reference's core elasticity invariant;
* workers are expected to resume from their latest checkpoint
  (``load_checkpoint(tag='latest')``), which is the reference's recovery
  path too — the agent guarantees a consistent relaunch env and, when a
  checkpoint dir is known, advertises the newest MANIFEST-VALID tag via
  ``DS_TPU_LAST_VALID_TAG`` so a torn newest tag cannot wedge recovery;
* restart hygiene for preemption storms: exponential backoff with jitter
  (capped), a restart-budget reset after a configurable stable-run
  window, and crash-loop detection (N failures inside T seconds aborts
  with a clear error instead of burning the budget on a doomed relaunch).
"""

import os
import random
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
from deepspeed_tpu.runtime import checkpoint_manifest
from deepspeed_tpu.runtime import constants as ds_constants
from deepspeed_tpu.utils.logging import logger


class ElasticAgentError(RuntimeError):
    pass


class CrashLoopError(ElasticAgentError):
    """Worker is failing faster than it can make progress; restarting
    again would only mask the root cause (e.g. a corrupt config, a
    permanently wedged checkpoint, an OOMing model)."""


class DSElasticAgent:
    """Supervise an elastic single-host-group training process.

    Parameters
    ----------
    cmd:
        argv of the training process (the agent prepends nothing; env
        carries the elastic state).
    ds_config:
        DeepSpeed-style config dict with an ``elasticity`` block; used to
        re-solve micro-batch/GAS per world size.
    discover_world:
        callback -> current world size (number of host processes). Defaults
        to the DS_TPU_NUM_PROCS env or 1. In a real deployment this queries
        the TPU slice/pod state after repair.
    max_restarts / backoff_s:
        restart budget for non-zero worker exits (preemption, slice loss).
        Delays grow exponentially from ``backoff_s`` (capped at
        ``max_backoff_s``) with ``±jitter`` relative noise so a pod's
        agents don't restart in lockstep after a shared outage.
    stable_window_s:
        when set, a worker that ran at least this long before failing
        resets the restart budget — long-lived jobs should survive any
        number of WELL-SPACED preemptions without exhausting a fixed
        budget. None keeps the strict cumulative budget.
    crash_loop_window_s / crash_loop_threshold:
        when the window is set, ``crash_loop_threshold`` failures inside
        it abort with :class:`CrashLoopError` — a persistently-crashing
        worker (bad config, wedged checkpoint) must fail loudly, not
        retry forever under a budget that stable-run resets keep
        refilling.
    ckpt_dir:
        checkpoint root; on every (re)launch the newest manifest-valid
        tag is exported as ``DS_TPU_LAST_VALID_TAG`` so the worker can
        recover even when the newest tag / 'latest' pointer is torn.
    divergence_exit_codes:
        exit codes that mean "training diverged past its rollback
        budget" (the sentinel's ``DivergenceError`` code, default 13) —
        restarting from the same checkpoint/data would replay the same
        divergence, so the agent returns immediately instead of burning
        the restart budget on it. A crash (any other non-zero code,
        including the hang watchdog's abort) stays restartable.
    """

    def __init__(self, cmd: List[str], ds_config: Dict,
                 discover_world: Optional[Callable[[], int]] = None,
                 max_restarts: int = 3, backoff_s: float = 5.0,
                 max_backoff_s: float = 60.0, jitter: float = 0.1,
                 stable_window_s: Optional[float] = None,
                 crash_loop_window_s: Optional[float] = None,
                 crash_loop_threshold: int = 3,
                 ckpt_dir: Optional[str] = None,
                 divergence_exit_codes=(
                     ds_constants.DIVERGENCE_EXIT_CODE_DEFAULT,),
                 env: Optional[Dict[str, str]] = None,
                 telemetry_dir: Optional[str] = None):
        self.cmd = list(cmd)
        self.ds_config = ds_config
        self.discover_world = discover_world or (
            lambda: int(os.environ.get("DS_TPU_NUM_PROCS", "1")))
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.jitter = jitter
        self.stable_window_s = stable_window_s
        self.crash_loop_window_s = crash_loop_window_s
        self.crash_loop_threshold = crash_loop_threshold
        self.ckpt_dir = ckpt_dir
        self.divergence_exit_codes = frozenset(
            int(c) for c in (divergence_exit_codes or ()))
        self.env = dict(env if env is not None else os.environ)
        # telemetry rendezvous dir: exported to workers (their flight
        # recorders dump blackboxes there) and swept into a run-level
        # crash report after every failure (docs/observability.md)
        self.telemetry_dir = telemetry_dir
        self.restart_count = 0
        self._failure_times: List[float] = []
        self._proc: Optional[subprocess.Popen] = None
        # world size of the previous incarnation when the topology changed
        # between launches (exported as DS_TPU_ELASTIC_PREV_WORLD so the
        # worker's load path expects a reshard); None once the world is
        # stable again
        self._prev_world: Optional[int] = None
        self._sleep = time.sleep  # seam for tests

    # ------------------------------------------------------------------
    def _worker_env(self, world: int) -> Dict[str, str]:
        env = dict(self.env)
        env["DS_TPU_NUM_PROCS"] = str(world)
        env["DS_TPU_ELASTIC_RESTART"] = str(self.restart_count)
        if self._prev_world is not None and self._prev_world != world:
            # topology changed since the last incarnation: the worker's
            # checkpoint load must expect (and verify) a reshard —
            # exported TOGETHER with the device count and the last valid
            # tag below, so the resume sees one consistent picture
            env[ds_constants.ELASTIC_PREV_WORLD_ENV] = str(self._prev_world)
        else:
            env.pop(ds_constants.ELASTIC_PREV_WORLD_ENV, None)
        if self.telemetry_dir:
            from deepspeed_tpu.telemetry.crash_report import (
                TELEMETRY_DIR_ENV)

            env[TELEMETRY_DIR_ENV] = self.telemetry_dir
        if self.ckpt_dir:
            # advertise the newest MANIFEST-VALID tag: the worker's
            # load_checkpoint falls back to it when the 'latest' pointer
            # is missing, and operators can inspect it in the env
            tag = checkpoint_manifest.latest_valid_tag(self.ckpt_dir)
            if tag is not None:
                env[checkpoint_manifest.LAST_VALID_TAG_ENV] = tag
                logger.info(f"elastic relaunch: last valid checkpoint "
                            f"tag is {tag}")
        elastic = self.ds_config.get("elasticity")
        if elastic and elastic.get("enabled"):
            # re-solve the batch triad for the new world size so
            # train_batch_size stays inside the elastic envelope
            chips = world * int(env.get("DS_TPU_CHIPS_PER_PROC", "1"))
            final_bs, _valid, micro = compute_elastic_config(
                self.ds_config, world_size=chips, return_microbatch=True)
            # the solver guarantees divisibility by micro * dp_world where
            # dp_world = chips / model_parallel_size (elasticity.py
            # pick_microbatch) — the exported triad must multiply back
            # exactly, else the effective batch silently shrinks and the
            # fixed-batch invariant this agent exists to guarantee breaks
            mp = int(elastic.get("model_parallel_size", 1))
            dp_world = max(1, chips // mp)
            if final_bs % (micro * dp_world):
                raise ElasticAgentError(
                    f"elastic config is inconsistent: batch {final_bs} is "
                    f"not divisible by micro*dp_world ({micro}*{dp_world})")
            gas = final_bs // (micro * dp_world)
            env["DS_TPU_ELASTIC_TRAIN_BATCH"] = str(final_bs)
            env["DS_TPU_ELASTIC_MICRO_BATCH"] = str(micro)
            env["DS_TPU_ELASTIC_GAS"] = str(gas)
            logger.info(
                f"elastic relaunch: world={world} batch={final_bs} "
                f"micro={micro} gas={gas}")
        return env

    def _launch(self, world: int) -> subprocess.Popen:
        return subprocess.Popen(self.cmd, env=self._worker_env(world))

    def _supervise_once(self, world: int) -> int:
        """Launch one incarnation and block until it exits (the loop
        body of :meth:`run`; :class:`DSWorldAgent` overrides it to
        supervise a whole multi-process world as one unit)."""
        self._proc = self._launch(world)
        return self._proc.wait()

    def _interrupt(self) -> None:
        """KeyboardInterrupt path: pass the SIGTERM along and reap."""
        if self._proc is not None:
            self._proc.send_signal(signal.SIGTERM)
            self._proc.wait()

    def _discover(self) -> int:
        world = self.discover_world()
        if world < 1:
            raise ElasticAgentError(f"discovered world size {world} < 1")
        return world

    def _next_backoff(self) -> float:
        """Exponential backoff with jitter: base * 2^(restarts-1), capped,
        then ±jitter relative noise (decorrelates agents across a pod)."""
        delay = min(self.backoff_s * (2 ** max(self.restart_count - 1, 0)),
                    self.max_backoff_s)
        if self.jitter > 0:
            delay *= 1.0 + random.uniform(-self.jitter, self.jitter)
        return max(delay, 0.0)

    def _check_crash_loop(self, now: float):
        if self.crash_loop_window_s is None:
            return
        cutoff = now - self.crash_loop_window_s
        self._failure_times = [t for t in self._failure_times if t >= cutoff]
        if len(self._failure_times) >= self.crash_loop_threshold:
            raise CrashLoopError(
                f"crash loop detected: {len(self._failure_times)} worker "
                f"failures within {self.crash_loop_window_s:.0f}s "
                f"(threshold {self.crash_loop_threshold}). The worker is "
                f"failing faster than it can make progress — aborting "
                f"instead of restarting; inspect the worker logs and the "
                f"checkpoint dir"
                + (f" ({self.ckpt_dir})" if self.ckpt_dir else "") + ".")

    # ------------------------------------------------------------------
    def run(self) -> int:
        """Supervision loop: returns the final exit code (0 on success).

        Raises :class:`CrashLoopError` when failures cluster tighter than
        ``crash_loop_threshold`` per ``crash_loop_window_s``."""
        while True:
            world = self._discover()
            started = time.monotonic()
            try:
                rc = self._supervise_once(world)
            except KeyboardInterrupt:
                self._interrupt()
                return 1
            if rc == 0:
                return 0
            self._sweep_crash_report(rc)
            if rc == ds_constants.PEER_LOSS_EXIT_CODE_DEFAULT:
                # the cluster health plane's coordinated abort: every
                # survivor exits 15 inside the silence budget, so THIS
                # failure is one world-level event, not a local crash.
                # Restartable — the relaunch resumes from the newest
                # manifest-valid tag; a permanently-gone peer changes
                # the discovered world below and takes the topology-
                # event path (immediate relaunch, no budget burned).
                meaning, _ = ds_constants.EXIT_CODE_MEANINGS[rc]
                logger.warning(
                    f"worker exited with code {rc} ({meaning}): "
                    f"relaunching the world together")
            if rc in self.divergence_exit_codes:
                logger.error(
                    f"worker exited with divergence code {rc}: training "
                    f"diverged past its rollback budget, and restarting "
                    f"from the same state would replay the same "
                    f"divergence — not restarting. Inspect the run "
                    f"(lr/data/precision)"
                    + (f" and the checkpoint dir ({self.ckpt_dir})"
                       if self.ckpt_dir else "") + ".")
                return rc
            now = time.monotonic()
            run_s = now - started
            new_world = self.discover_world()
            if new_world >= 1 and new_world != world:
                # the slice was repaired to a different size: the worker
                # died BECAUSE the topology changed, not because it is
                # sick. Restart immediately on the new world — no failure
                # accounting, no backoff, no restart-budget consumption —
                # and tell the next incarnation what the old world was so
                # its checkpoint load expects a reshard. Failures at a
                # STABLE world still count toward the crash-loop guard.
                self._prev_world = world
                logger.warning(
                    f"worker failed (rc={rc}) and the discovered world "
                    f"changed {world} -> {new_world}: treating as a "
                    f"topology change, not a crash; relaunching "
                    f"immediately with elastic reshard expected")
                continue
            self._prev_world = None
            self._failure_times.append(now)
            self._check_crash_loop(now)
            if (self.stable_window_s is not None
                    and run_s >= self.stable_window_s
                    and self.restart_count > 0):
                logger.info(
                    f"worker ran {run_s:.0f}s (>= stable window "
                    f"{self.stable_window_s:.0f}s) before failing; "
                    f"resetting restart budget")
                self.restart_count = 0
            if self.restart_count >= self.max_restarts:
                logger.error(
                    f"worker failed (rc={rc}) and restart budget "
                    f"({self.max_restarts}) is exhausted")
                return rc
            self.restart_count += 1
            delay = self._next_backoff()
            logger.warning(
                f"worker failed (rc={rc}) after {run_s:.1f}s; elastic "
                f"restart {self.restart_count}/{self.max_restarts} in "
                f"{delay:.1f}s")
            if delay > 0:
                self._sleep(delay)

    def _sweep_crash_report(self, rc: int) -> None:
        """Merge the workers' blackbox dumps into ``crash-report.json``.

        Called after every non-zero worker exit: even if the agent then
        restarts, the report snapshots what the last incarnation left
        behind (the next crash's dumps overwrite per-rank files, and the
        sweep re-runs). Never raises — forensics must not change the
        supervision outcome."""
        if not self.telemetry_dir:
            return
        try:
            from deepspeed_tpu.telemetry.crash_report import (
                sweep_blackbox_dumps)

            report = sweep_blackbox_dumps(self.telemetry_dir)
        except Exception as e:  # pragma: no cover
            logger.warning(f"blackbox sweep failed: {e}")
            return
        if report is None:
            logger.info(
                f"worker exited rc={rc} but left no blackbox dump under "
                f"{self.telemetry_dir} (crash before telemetry armed, or "
                f"dumps disabled)")
            return
        logger.error(
            f"crash report: {report['path']} — {report['num_ranks']} "
            f"rank(s), reasons={report['reasons']}, last step "
            f"{report['last_step_min']}..{report['last_step_max']}, "
            f"first fatal rank {report['first_fatal_rank']}")


def _free_port(host: str = "127.0.0.1") -> int:
    """Ask the kernel for a currently-free TCP port (the standard
    bind-to-0 trick). Used to mint a fresh coordinator port per world
    incarnation so a relaunch never races the dying rendezvous of the
    previous one in TIME_WAIT."""
    import socket

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class DSWorldAgent(DSElasticAgent):
    """Supervise ALL processes of one training world as a single unit.

    The per-host :class:`DSElasticAgent` cannot express the cluster
    health plane's contract (docs/recovery.md "Cluster health & SDC
    defense"): when one process of a multi-process world is lost — or
    wedged, so its peers abort with
    :data:`constants.PEER_LOSS_EXIT_CODE_DEFAULT` — the WORLD must
    relaunch together. ``jax.distributed`` rendezvous needs every
    process present; a survivor restarted alone would just park in the
    coordinator barrier. This agent therefore:

    * launches ``world`` workers, each with its own ``DS_TPU_PROC_ID``
      and a shared freshly-minted ``DS_TPU_COORDINATOR`` port (a new
      port per incarnation, so relaunch N+1 cannot collide with the
      half-dead rendezvous of incarnation N);
    * waits for the FIRST abnormal exit and then SIGKILLs the remaining
      workers — SIGKILL, not SIGTERM, because a SIGSTOP-wedged or
      collective-hung process cannot honor a catchable signal;
    * feeds that single exit code into the base class's restart policy,
      so one coordinated failure costs exactly ONE restart (and one
      ``world_relaunches`` tick, which the chaos bench asserts on).
    """

    def __init__(self, cmd: List[str], ds_config: Dict,
                 coordinator_host: str = "127.0.0.1",
                 port_factory: Optional[Callable[[], int]] = None,
                 **kwargs):
        super().__init__(cmd, ds_config, **kwargs)
        self.coordinator_host = coordinator_host
        self._port_factory = port_factory or (
            lambda: _free_port(self.coordinator_host))
        self._procs: List[subprocess.Popen] = []
        self._worlds_launched = 0
        # world-level relaunches performed (== launches - 1): the chaos
        # bench asserts a coordinated exit-15 costs exactly ONE of these
        self.world_relaunches = 0

    # ------------------------------------------------------------------
    def _rank_env(self, world: int, rank: int, port: int) -> Dict[str, str]:
        env = self._worker_env(world)
        env["DS_TPU_PROC_ID"] = str(rank)
        env["DS_TPU_COORDINATOR"] = f"{self.coordinator_host}:{port}"
        return env

    def _supervise_once(self, world: int) -> int:
        port = self._port_factory()
        self._worlds_launched += 1
        if self._worlds_launched > 1:
            self.world_relaunches += 1
        logger.info(
            f"world agent: launching world of {world} process(es) "
            f"(incarnation {self._worlds_launched}, coordinator "
            f"{self.coordinator_host}:{port})")
        self._procs = [
            subprocess.Popen(self.cmd, env=self._rank_env(world, r, port))
            for r in range(world)
        ]
        try:
            return self._wait_world()
        finally:
            self._reap()

    def _wait_world(self) -> int:
        """Block until the world resolves: 0 when every worker exited
        cleanly, else the exit code of the FIRST abnormal worker (the
        caller SIGKILLs the rest — they are either about to exit with
        the same coordinated code or wedged beyond signaling)."""
        pending = set(range(len(self._procs)))
        while pending:
            progressed = False
            for i in sorted(pending):
                rc = self._procs[i].poll()
                if rc is None:
                    continue
                pending.discard(i)
                progressed = True
                if rc != 0:
                    logger.warning(
                        f"world agent: rank {i} exited rc={rc}; tearing "
                        f"down the remaining {len(pending)} worker(s)")
                    return rc
            if pending and not progressed:
                self._sleep(0.05)
        return 0

    def _reap(self) -> None:
        """SIGKILL and reap every still-running worker. SIGKILL cannot
        be blocked and — unlike SIGTERM — acts on a SIGSTOPed process
        without a prior SIGCONT, which is exactly the wedged-peer case
        this agent exists for."""
        for proc in self._procs:
            if proc.poll() is None:
                try:
                    proc.kill()
                except OSError:  # already gone
                    pass
        for proc in self._procs:
            try:
                proc.wait(timeout=30)
            except Exception:  # pragma: no cover - kernel-level wedge
                logger.error(
                    f"world agent: worker pid {proc.pid} did not reap "
                    f"after SIGKILL")

    def _interrupt(self) -> None:
        for proc in self._procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        self._reap()


def main(argv=None) -> int:
    """CLI: ``python -m deepspeed_tpu.elasticity.elastic_agent [--config
    ds_config.json] -- cmd ...``"""
    import argparse
    import json

    p = argparse.ArgumentParser()
    p.add_argument("--config", default=None)
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--backoff", type=float, default=5.0)
    p.add_argument("--max_backoff", type=float, default=60.0)
    p.add_argument("--jitter", type=float, default=0.1)
    p.add_argument("--stable_window", type=float, default=None,
                   help="seconds of stable running that reset the "
                        "restart budget (default: never reset)")
    p.add_argument("--crash_loop_window", type=float, default=None,
                   help="abort when --crash_loop_threshold failures land "
                        "within this many seconds")
    p.add_argument("--crash_loop_threshold", type=int, default=3)
    p.add_argument("--ckpt_dir", default=None,
                   help="checkpoint root; the newest manifest-valid tag "
                        "is exported to workers as DS_TPU_LAST_VALID_TAG")
    p.add_argument("--telemetry_dir", default=None,
                   help="flight-recorder dir exported to workers as "
                        "DS_TPU_TELEMETRY_DIR; per-rank blackbox dumps "
                        "are swept into crash-report.json on failure")
    p.add_argument("--divergence_exit_code", type=int, action="append",
                   default=None,
                   help="worker exit code meaning 'training diverged' — "
                        "the agent returns instead of restarting into "
                        "the same divergence (repeatable; default "
                        f"{ds_constants.DIVERGENCE_EXIT_CODE_DEFAULT})")
    p.add_argument("cmd", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        p.error("no worker command given")
    cfg = {}
    if args.config:
        with open(args.config) as f:
            cfg = json.load(f)
    agent = DSElasticAgent(
        cmd, cfg, max_restarts=args.max_restarts, backoff_s=args.backoff,
        max_backoff_s=args.max_backoff, jitter=args.jitter,
        stable_window_s=args.stable_window,
        crash_loop_window_s=args.crash_loop_window,
        crash_loop_threshold=args.crash_loop_threshold,
        ckpt_dir=args.ckpt_dir,
        divergence_exit_codes=(
            args.divergence_exit_code if args.divergence_exit_code
            else (ds_constants.DIVERGENCE_EXIT_CODE_DEFAULT,)),
        telemetry_dir=args.telemetry_dir)
    return agent.run()


if __name__ == "__main__":
    sys.exit(main())

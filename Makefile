# Self-running gates (reference wires the same split into
# .github/workflows/: formatting + unit suites + op pre-compile; here the
# TPU-facing perf gate is the extra axis).
#
#   make quick   fast confidence: imports + the fast unit subset
#                (<5 min, virtual CPU mesh, `-m "not slow"`) — what the
#                pre-push hook runs
#   make test    full unit suite on the 8-device virtual CPU mesh
#   make smoke   perf regression gate on the real chip
#                (benchmarks/smoke.py vs committed expected.json, +-10%)
#   make chaos   fault-injection suite: torn/failed checkpoint writes,
#                preemption grace saves, crash-loop detection, elastic
#                topology resume (8->4 / 4->8 kill-and-reshard), the
#                training health sentinel: NaN/spike anomalies, auto-
#                rollback, hang watchdog (docs/recovery.md), the
#                serving-fleet failover units, and the cluster health
#                plane units (silence schedule, coordinated abort, SDC
#                digest cross-check) — runs chaos-cluster first
#   make chaos-serve  kill-a-replica-mid-decode scenario: one of N
#                serving replicas is SIGKILLed while decoding; asserts
#                zero lost requests, token-identical failover replays,
#                and one serve.failover per migrated request (commits
#                benchmarks/inference/failover_bench_results.json)
#   make chaos-cluster  cluster-health scenarios on a REAL 2-process
#                world: SIGSTOP one rank of a pp=2 run (survivor exits
#                15 within the silence budget, ONE world relaunch,
#                resume on-trajectory) and a silent bit flip in a
#                replicated weight (digest probe catches it within K
#                steps, crc-valid blackbox, rollback on-trajectory) —
#                docs/recovery.md "Cluster health & SDC defense"
#                (commits benchmarks/chaos_cluster_results.json)
#   make profile step-profiler gate on a tiny CPU config: asserts phase
#                breakdown sums to step wall time, analytic MFU from the
#                compiled step, and a perfetto-loadable trace
#                (docs/observability.md)
#   make blackbox crash-forensics gate: injected NaN divergence must
#                leave a crc-valid flight-recorder blackbox (>=32 step
#                records with phases/loss/comm + compiled memory) and a
#                sweepable run-level crash report (docs/observability.md)
#   make memreport  analytic HBM report for the 1.3B seq-1024 train step
#                from avals-only AOT compile (docs/performance.md
#                "The 1.3B memory ceiling")
#   make serve-bench  serving front door under the bursty prefix-skewed
#                trace: CB+prefix-cache vs cold CB vs sequential (TTFT /
#                tok/s / hit rate, CPU backend, commits benchmarks/
#                inference/serving_bench_prefix_results.json)
#   make serve-bench-uniform  the original uniform-trace CB-vs-sequential
#                comparison (serving_bench_results.json)
#   make serve-bench-disagg  disaggregated topology on the bursty trace:
#                prefill/decode split vs front door, int8-KV + spec-
#                decode tier, lanes-per-replica capacity table (commits
#                benchmarks/inference/serving_bench_disagg_results.json)
#   make data-bench  packed input pipeline: dataloader+h2d phase share
#                with background prefetch off vs on (commits
#                benchmarks/data/input_pipeline_bench_results.json)
#   make dryrun  the multi-axis mesh gate (__graft_entry__.dryrun_
#                multichip(8)) with per-phase wall clock; commits
#                benchmarks/dryrun_phase_times.json and fails if the
#                total breaches the 5-minute budget
#   make mfu-search  CPU-safe live step-config search: tiny GPT over the
#                (remat x micro x flash) grid with a tight HBM override
#                (prune path exercised for real), winner trained under
#                the step profiler (docs/performance.md "Step
#                autotuner"); artifact + trace to /tmp
#   make mfu-search-full  the committed 1.3B seq-1024 artifact: avals-
#                only AOT grid vs the TPU v4 HBM ceiling + calibrated
#                roofline MFU (benchmarks/mfu_search_results.json,
#                ~5 min of CPU compiles)
#   make overlap-measured  wall-clock bucketed-vs-monolithic exchange
#                deltas (benchmarks/communication/
#                overlap_measured_results.json); nonzero exit when
#                bucketed-on regresses beyond the measured noise band
#   make hierarchical-exchange  ICI/DCN two-level exchange gate: per-
#                level wire bytes (int8 DCN leg <= 0.3x flat bf16) and
#                wall clock within the monolithic int8 baseline's
#                3-sigma band (benchmarks/communication/
#                hierarchical_exchange_results.json); nonzero exit past
#                either bound
#   make check   test + smoke-if-hot-paths-changed — the full gate
#   make hooks   install the committed .githooks (pre-push runs
#                `make quick` + conditional smoke)

PY ?= python
# hot paths whose changes require the perf gate (the r3 regression lesson:
# a timing change in any of these shipped unnoticed for a round)
HOT_PATHS := deepspeed_tpu/runtime/engine.py deepspeed_tpu/models \
             deepspeed_tpu/ops deepspeed_tpu/utils/timer.py \
             deepspeed_tpu/inference/engine.py \
             deepspeed_tpu/runtime/step_autotune.py

.PHONY: quick test smoke chaos chaos-serve chaos-cluster profile \
        blackbox memreport \
        check hooks hot-changed serve-bench serve-bench-uniform \
        serve-bench-disagg data-bench \
        dryrun mfu-search mfu-search-full overlap-measured \
        hierarchical-exchange

# the <5-min smoke tier: config/mesh/kernels plus the comm + autotune +
# process-group units, with tests marked `slow` (pyproject marker) opted
# out — mark compile-heavy tests slow rather than dropping whole files
quick:
	$(PY) -c "import deepspeed_tpu; import __graft_entry__; print('imports ok')"
	$(PY) -m pytest tests/unit/test_config.py tests/unit/test_mesh.py \
	  tests/unit/test_ops.py tests/unit/test_comm.py \
	  tests/unit/test_compressed_comm.py tests/unit/test_bucketed_comm.py \
	  tests/unit/test_grad_exchange_modes.py \
	  tests/unit/test_pipe_transport.py \
	  tests/unit/test_flash_autotune.py tests/unit/test_procgroup.py \
	  tests/unit/test_launcher.py tests/unit/test_serving.py \
	  tests/unit/test_serving_frontdoor.py \
	  tests/unit/test_serving_fleet.py \
	  tests/unit/test_serving_disagg.py \
	  tests/unit/test_data_pipeline.py tests/unit/test_telemetry.py \
	  tests/unit/test_step_autotune.py \
	  tests/unit/test_elastic_reshard.py \
	  tests/unit/test_health_state.py tests/unit/test_cluster_health.py \
	  -q -x -m "not slow"

test:
	$(PY) -m pytest tests/ -q

smoke:
	$(PY) benchmarks/smoke.py

# includes the elastic 8->4 / 4->8 topology-resume scenarios (train on N
# virtual devices, kill mid-epoch, resume on N' — docs/recovery.md
# "Elastic topology resume"); the slow marker is NOT excluded here
chaos: chaos-cluster
	$(PY) -m pytest tests/unit/test_fault_tolerance.py tests/unit/test_sentinel.py \
	  tests/unit/test_elastic_reshard.py tests/unit/test_serving_fleet.py \
	  tests/unit/test_health_state.py tests/unit/test_cluster_health.py -q

# wedge-one-rank / flip-one-bit scenarios on a real two-process world
# under the world agent (docs/recovery.md "Cluster health & SDC
# defense"); exits nonzero if any survivor hangs instead of aborting 15,
# the world relaunches more than once, the digest probe misses the
# corruption, or the resumed losses leave the reference trajectory
chaos-cluster:
	JAX_PLATFORMS=cpu $(PY) benchmarks/chaos_cluster.py

# serving-fleet kill scenario: three runs over one trace (in-process
# reference, fleet baseline, fleet with a mid-decode SIGKILL) proving
# the exact-failover contract end to end (docs/recovery.md "Serving
# failover"); exits nonzero on any lost request or token divergence
chaos-serve:
	JAX_PLATFORMS=cpu $(PY) benchmarks/inference/chaos_serve.py

profile:
	$(PY) benchmarks/profile_step.py

blackbox:
	JAX_PLATFORMS=cpu $(PY) benchmarks/blackbox_check.py

memreport:
	JAX_PLATFORMS=cpu $(PY) benchmarks/memory_report.py \
	  --out benchmarks/memory_report_1p3b.json

# multi-axis mesh gate with committed per-phase wall clock; the child
# writes the artifact, and dryrun_multichip itself fails the run when
# total exceeds DS_TPU_DRYRUN_TOTAL_BUDGET_S (default 300s)
dryrun:
	DS_TPU_DRYRUN_TIMES_OUT=benchmarks/dryrun_phase_times.json \
	  $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# CPU-safe seconds-scale search (small model, live prune + profiler
# trace); the committed 1.3B artifact comes from mfu-search-full
mfu-search:
	JAX_PLATFORMS=cpu $(PY) benchmarks/mfu_search.py --mode small \
	  --out /tmp/mfu_search_small.json

mfu-search-full:
	JAX_PLATFORMS=cpu $(PY) benchmarks/mfu_search.py --mode full

overlap-measured:
	JAX_PLATFORMS=cpu $(PY) benchmarks/communication/overlap_measured.py

hierarchical-exchange:
	JAX_PLATFORMS=cpu $(PY) benchmarks/communication/hierarchical_exchange.py

# the serving front-door headline: bursty prefix-skewed trace through
# CB+prefix-cache vs cold CB vs sequential generate (docs/performance.md
# "Serving"). Runs on the virtual CPU backend; writes benchmarks/
# inference/serving_bench_prefix_results.json and exits nonzero unless
# prefix p95 TTFT strictly beats cold CB with a positive hit rate.
serve-bench:
	JAX_PLATFORMS=cpu $(PY) benchmarks/inference/serving_prefix_bench.py

# the original uniform-trace comparison (CB vs sequential, no prefix
# reuse); writes benchmarks/inference/serving_bench_results.json.
serve-bench-uniform:
	JAX_PLATFORMS=cpu $(PY) benchmarks/inference/serving_bench.py

# disaggregated serving on the same bursty trace: prefill/decode split
# (DisaggServer + KV hand-off) vs the front door, plus int8-KV + spec-
# decode decode tier and the lanes-per-replica capacity table
# (docs/performance.md "Disaggregated serving"). Writes benchmarks/
# inference/serving_bench_disagg_results.json; exits nonzero unless
# disagg tokens are identical to the front door's, int8 capacity beats
# bf16 >= 1.7x / fp32 >= 3.0x, and spec acceptance >= 0.5.
serve-bench-disagg:
	JAX_PLATFORMS=cpu $(PY) benchmarks/inference/serving_disagg_bench.py

# packed input pipeline: dataloader+h2d share of step time with
# data_pipeline.prefetch off vs on (docs/data.md). Writes
# benchmarks/data/input_pipeline_bench_results.json; exits nonzero when
# prefetch fails to reduce the input share.
data-bench:
	JAX_PLATFORMS=cpu $(PY) benchmarks/data/input_pipeline_bench.py

# exits 0 when any hot-path file differs from BASE (override: `make
# hot-changed BASE=<sha>` — the pre-push hook passes the remote sha so a
# multi-commit push is diffed as a RANGE, not just the last commit).
# Fallback order: origin/main, then HEAD~1; if neither resolves, report
# changed — running the gate needlessly is the safe failure mode.
BASE ?=
hot-changed:
	@base="$(BASE)"; \
	if [ -z "$$base" ]; then \
	  base=$$(git rev-parse --verify -q origin/main \
	          || git rev-parse --verify -q 'HEAD~1') || true; \
	fi; \
	if [ -z "$$base" ]; then \
	  echo "no base to diff against; treating hot paths as changed"; \
	  exit 0; \
	fi; \
	if git diff --name-only "$$base" -- $(HOT_PATHS) | grep -q .; then \
	  echo "hot paths changed since $$base"; exit 0; \
	else \
	  echo "no hot-path changes"; exit 1; \
	fi

check: test
	@if $(MAKE) -s hot-changed; then $(MAKE) smoke; else \
	  echo "skipping smoke (no hot-path changes)"; fi

hooks:
	git config core.hooksPath .githooks
	@echo "hooks installed: pre-push runs 'make quick' + conditional smoke"

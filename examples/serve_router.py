#!/usr/bin/env python
"""Fault-tolerant multi-replica serving: prefix-affine routing, live
token streaming, and exact failover when a replica is killed mid-decode.

Each replica process owns one ``InferenceEngine`` + continuous-batching
scheduler with its own prefix cache, bounded queue, and SLO admission
controller (one replica == one accelerator's serving loop; here the
replicas run on the CPU backend so the demo works anywhere). The parent
is the front door: ``FleetCoordinator`` routes requests hash-affine over
the LIVE replicas, journals every delivered token, and when a replica's
pipe hits EOF (its process died) migrates that replica's in-flight
requests to survivors as exact replays — the survivor re-prefills
``prompt + delivered tokens`` at the original pad offset, so greedy
continuations are token-identical to the run that died.

Wire protocol (one pipe per replica; messages, never blocking RPC):
    parent -> child: ("submit", rid, prompt, max_new, replay|None)
                     ("quit",)
    child -> parent: ("hello", pid)            once, after engine build
                     ("tok", rid, token, done) per DELIVERED token
                     ("shed", rid, reason)     admission rejected it
                     ("idle", pending)         run() drained its queue

The child pumps its pipe BETWEEN decode steps (``run(poll_fn=...)``),
so a failover replay lands in a survivor's free lane while it is still
decoding its own work — no stop-the-world hand-off. The parent never
issues a blocking request to a child (the depth probe of the old demo
is replaced by journal-derived depths), so a dead child can never hang
the front door: its death is an EOF, not a timeout.

All replicas load IDENTICAL weights (same seed): exact failover replay
is only meaningful when the survivor computes the same function as the
deceased. Real fleets get this from a shared checkpoint.

Run:  JAX_PLATFORMS=cpu python examples/serve_router.py [--replicas 2]
          [--kill-replica auto | N | none] [--kill-after-tokens 6]
"""

import argparse
import json
import multiprocessing as mp
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# one serving config for every replica AND the bench's in-process
# reference run — completions can only be compared across runs that
# bucket, cache, and admit identically
SERVING_CFG = {
    "slots": 4,
    "max_pending": 64,
    "prefix_cache": {"promote_after": 2},
    "admission": {"slo_ttft_p95_s": 30.0},  # generous: CPU demo
}


def build_engine(seed: int = 0):
    """The demo's tiny ring-attention engine (shared with the chaos
    bench so its reference run uses byte-identical weights)."""
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.transformer_lm import GPT, GPTConfig
    from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import \
        apply_sparse_attention

    cfg = GPTConfig(vocab_size=512, n_positions=512, n_embd=64, n_layer=2,
                    n_head=4, dtype=jnp.float32, rotary=True,
                    learned_positions=False, scan_layers=True)
    model = apply_sparse_attention(
        GPT(cfg), {"mode": "local_sliding_window", "block": 16,
                   "num_sliding_window_blocks": 3})
    return deepspeed_tpu.init_inference(model, dtype="fp32", seed=seed)


def replica_main(conn, seed: int, serving_cfg=None):
    """One scheduler replica: serve whatever the front door streams in
    until ("quit",) or the pipe dies."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # own session => own process group, so the parent's
    # reap_process_group(pid) can kill this replica's whole tree
    # without signalling the parent
    os.setsid()

    from deepspeed_tpu.serving import AdmissionRejected, build_serving

    eng = build_engine(seed)
    sched = build_serving(eng, dict(serving_cfg or SERVING_CFG))
    state = {"quit": False}

    def handle(msg) -> None:
        if msg[0] == "submit":
            _, rid, prompt, max_new, replay = msg

            def cb(_local_rid, token, done, _rid=rid):
                conn.send(("tok", _rid, int(token), bool(done)))

            try:
                sched.submit(prompt, max_new_tokens=max_new,
                             stream_callback=cb, replay_tokens=replay)
            except AdmissionRejected as e:
                conn.send(("shed", rid, e.reason))
        elif msg[0] == "quit":
            state["quit"] = True

    def pump():
        # called between decode steps: failover replays submitted while
        # this replica is mid-run land in its free lanes immediately
        while conn.poll(0):
            handle(conn.recv())

    conn.send(("hello", os.getpid()))
    try:
        while not state["quit"]:
            handle(conn.recv())
            if state["quit"]:
                break
            if sched._pending:
                sched.run(poll_fn=pump)
                if not state["quit"]:
                    conn.send(("idle", len(sched._pending)))
    except (EOFError, OSError):
        pass  # front door died; nothing left to serve
    conn.close()


def run_fleet(prompts, max_new: int = 8, replicas: int = 2, seed: int = 0,
              kill_replica=None, kill_after_tokens: int = 6,
              serving_cfg=None, verbose: bool = True):
    """Serve ``prompts`` across ``replicas`` child processes; optionally
    hard-kill one replica after it has delivered ``kill_after_tokens``
    tokens, and failover its in-flight requests. Returns completions
    (request id -> delivered tokens, replay prefix included) plus fleet
    and router stats. ``kill_replica`` is an index, ``"auto"`` (the
    replica holding the most requests), or None."""
    from multiprocessing import connection as mpc

    from deepspeed_tpu.serving import (FleetCoordinator, FleetHealth,
                                       HealthConfig, PrefixRouter)
    from deepspeed_tpu.utils.procgroup import reap_process_group

    n = int(replicas)
    router = PrefixRouter(n, align=16, spill_slack=2)
    # the pipe EOF is the authoritative death signal here, so the
    # silence timers are set far beyond the demo's runtime — an idle
    # replica (blocked in recv between bursts) is not a dead one
    health = FleetHealth(n, HealthConfig(suspect_after_s=60.0,
                                         down_after_s=600.0))
    coord = FleetCoordinator(router, health=health)

    ctx = mp.get_context("spawn")  # fresh jax per replica
    conns, procs, pids = [], [], {}
    for i in range(n):
        parent_c, child_c = ctx.Pipe()
        p = ctx.Process(target=replica_main,
                        args=(child_c, seed, serving_cfg), daemon=True)
        p.start()
        # the parent MUST drop its copy of the child end, or the pipe
        # never EOFs when the child dies (the old demo's hang)
        child_c.close()
        conns.append(parent_c)
        procs.append(p)
    alive = [True] * n
    for i, c in enumerate(conns):
        msg = c.recv()  # ("hello", pid) — blocks until the engine built
        pids[i] = msg[1]
        coord.health.heartbeat(i)

    placements = []
    for rid, prompt in enumerate(prompts):
        replica, how = coord.place(rid, list(prompt), max_new)
        conns[replica].send(("submit", rid, list(prompt), max_new, None))
        placements.append((replica, how))
    if kill_replica == "auto":
        by_load = [sum(1 for r, _ in placements if r == i)
                   for i in range(n)]
        kill_replica = max(range(n), key=lambda i: by_load[i])
    killed = None
    tokens_from = [0] * n

    def on_dead(i: int):
        alive[i] = False
        conns[i].close()
        moved = coord.replica_dead(i, reason="eof")
        if verbose:
            print(f"replica {i} died: migrating {len(moved)} in-flight "
                  "request(s) to survivors")
        for rid, target, spec in moved:
            conns[target].send(("submit", rid, spec["prompt"],
                                spec["max_new_tokens"],
                                spec["replay_tokens"]))

    while coord.journal.stats()["inflight"] > 0:
        ready = mpc.wait([c for i, c in enumerate(conns) if alive[i]],
                         timeout=1.0)
        if not ready:
            if not any(alive):
                break  # every replica died with work outstanding
            continue
        for c in ready:
            i = conns.index(c)
            try:
                msg = c.recv()
            except (EOFError, OSError):
                # recv drains buffered messages before raising, so
                # every token that made it onto the wire was journaled
                # — the replay cut is exactly the delivered prefix
                on_dead(i)
                continue
            coord.health.heartbeat(i)
            if msg[0] == "tok":
                _, rid, token, done = msg
                coord.on_token(rid, token, done=done)
                tokens_from[i] += 1
                if (killed is None and kill_replica == i
                        and tokens_from[i] >= kill_after_tokens):
                    killed = i
                    if verbose:
                        print(f"killing replica {i} mid-decode (after "
                              f"{tokens_from[i]} delivered tokens)")
                    reap_process_group(pids[i], term_timeout=2.0,
                                       kill_timeout=5.0)
            elif msg[0] == "shed":
                coord.journal.record_shed(msg[1])
                if verbose:
                    print(f"request {msg[1]} shed by replica {i}: {msg[2]}")

    for i, c in enumerate(conns):
        if alive[i]:
            try:
                c.send(("quit",))
            except (BrokenPipeError, OSError):
                pass
    for i, p in enumerate(procs):
        p.join(timeout=30)
        reap_process_group(pids[i], term_timeout=3.0, kill_timeout=5.0)

    completions, per_request = {}, {}
    for rid in range(len(prompts)):
        e = coord.journal.entry(rid)
        if e is None:
            continue
        completions[rid] = list(e.emitted)
        per_request[rid] = {
            "replica": e.replica, "failovers": e.failovers,
            "done": e.done, "shed": e.shed,
            "ttft_s": (None if e.t_first_token is None
                       else e.t_first_token - e.t_submit),
        }
    return {
        "completions": completions,
        "per_request": per_request,
        "placements": placements,
        "killed_replica": killed,
        "fleet": coord.stats(),
        "router": router.stats(),
        "health_transitions": [(i, frm, to) for _, i, frm, to
                               in coord.health.transitions],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--kill-replica", default="auto",
                    help="'auto', a replica index, or 'none'")
    ap.add_argument("--kill-after-tokens", type=int, default=6)
    args = ap.parse_args()

    from benchmarks.inference.prefix_trace import make_bursty_prefix_trace

    # block must match the replicas' layout block (16 in the tiny model)
    prompts, meta = make_bursty_prefix_trace(
        args.requests, block=16, seed=0, num_prefixes=2,
        prefix_blocks=(4, 2), weights=(0.7, 0.3), suffix_base=9,
        burst_len=3, vocab=512)
    kill = args.kill_replica
    if kill == "none":
        kill = None
    elif kill != "auto":
        kill = int(kill)

    t0 = time.monotonic()
    out = run_fleet(prompts, max_new=args.max_new, replicas=args.replicas,
                    kill_replica=kill,
                    kill_after_tokens=args.kill_after_tokens)
    done = sum(1 for r in out["per_request"].values()
               if r["done"] and not r["shed"])
    migrated = sum(1 for r in out["per_request"].values()
                   if r["failovers"] > 0)
    print(json.dumps({
        "replicas": args.replicas,
        "requests": args.requests,
        "trace_prefix_lens": meta["prefix_lens"],
        "killed_replica": out["killed_replica"],
        "completed": done,
        "migrated": migrated,
        "lost": args.requests - done,
        "served_tokens": sum(len(t) for t in out["completions"].values()),
        "router": out["router"],
        "health_transitions": out["health_transitions"],
        "wall_s": round(time.monotonic() - t0, 2),
    }, indent=2))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Multi-replica serving front door: prefix-affine routing over N
scheduler replicas in separate processes.

Each replica process owns one ``InferenceEngine`` + continuous-batching
scheduler with its own prefix cache, bounded queue, and SLO admission
controller (one replica == one accelerator's serving loop; here the
replicas run on the CPU backend so the demo works anywhere). The parent
is the front door: it routes a bursty prefix-skewed trace with
``PrefixRouter`` — hash-affine on the prompt's leading block so one
tenant's requests land where their prefix is warm, spilling to the
shallowest queue when the home replica is overloaded — and aggregates
per-replica serving stats, prefix hit rates, and shed counts.

Wire protocol (pipe per replica, parent -> child):
    ("submit", prompt, max_new)   -> ("ok", rid) | ("shed", reason)
    ("depth",)                    -> ("depth", n)
    ("run",)                      -> ("done", summary, frontdoor_stats)
    ("quit",)                     -> child exits

Run:  JAX_PLATFORMS=cpu python examples/serve_router.py [--replicas 2]
"""

import argparse
import json
import multiprocessing as mp
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def replica_main(conn, seed: int):
    """One scheduler replica: build a tiny ring-attention engine and
    serve whatever the front door sends until ("quit",)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.transformer_lm import GPT, GPTConfig
    from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import \
        apply_sparse_attention
    from deepspeed_tpu.serving import AdmissionRejected, build_serving

    cfg = GPTConfig(vocab_size=512, n_positions=512, n_embd=64, n_layer=2,
                    n_head=4, dtype=jnp.float32, rotary=True,
                    learned_positions=False, scan_layers=True)
    model = apply_sparse_attention(
        GPT(cfg), {"mode": "local_sliding_window", "block": 16,
                   "num_sliding_window_blocks": 3})
    eng = deepspeed_tpu.init_inference(model, dtype="fp32", seed=seed)
    sched = build_serving(eng, {
        "slots": 4,
        "max_pending": 64,
        "prefix_cache": {"promote_after": 2},
        "admission": {"slo_ttft_p95_s": 30.0},  # generous: CPU demo
    })
    while True:
        msg = conn.recv()
        if msg[0] == "submit":
            _, prompt, max_new = msg
            try:
                rid = sched.submit(prompt, max_new_tokens=max_new)
                conn.send(("ok", rid))
            except AdmissionRejected as e:
                conn.send(("shed", e.reason))
        elif msg[0] == "depth":
            conn.send(("depth", len(sched._pending)))
        elif msg[0] == "run":
            stats = sched.run()
            conn.send(("done", stats.summary(), sched.frontdoor_stats()))
        elif msg[0] == "quit":
            conn.close()
            return


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--max-new", type=int, default=8)
    args = ap.parse_args()

    from benchmarks.inference.prefix_trace import make_bursty_prefix_trace
    from deepspeed_tpu.serving import PrefixRouter

    # block must match the replicas' layout block (16 in the tiny model)
    prompts, meta = make_bursty_prefix_trace(
        args.requests, block=16, seed=0, num_prefixes=2,
        prefix_blocks=(4, 2), weights=(0.7, 0.3), suffix_base=9,
        burst_len=3, vocab=512)
    router = PrefixRouter(args.replicas, align=16, spill_slack=2)

    ctx = mp.get_context("spawn")  # fresh jax per replica
    conns, procs = [], []
    for i in range(args.replicas):
        parent, child = ctx.Pipe()
        p = ctx.Process(target=replica_main, args=(child, i), daemon=True)
        p.start()
        conns.append(parent)
        procs.append(p)

    def depth(i):
        conns[i].send(("depth",))
        return conns[i].recv()[1]

    placed, shed = [], 0
    for prompt in prompts:
        depths = [depth(i) for i in range(args.replicas)]
        r, how = router.route(prompt, depths)
        conns[r].send(("submit", prompt, args.max_new))
        reply = conns[r].recv()
        if reply[0] == "shed":
            shed += 1
            print(f"request shed by replica {r}: {reply[1]}")
        else:
            placed.append((r, how))

    for c in conns:
        c.send(("run",))
    totals = {"tokens": 0, "sequences": 0}
    for i, c in enumerate(conns):
        _, summary, fd = c.recv()
        totals["tokens"] += summary["total_generated_tokens"]
        totals["sequences"] += summary["num_sequences"]
        print(f"replica {i}: {summary['num_sequences']} seqs, "
              f"{summary['total_generated_tokens']} tokens, "
              f"ttft p95 {summary['ttft_s']['p95'] * 1e3:.0f}ms, "
              f"prefix hit rate "
              f"{fd['prefix']['hit_rate']:.2f}, shed {fd['shed']}")
    for c in conns:
        c.send(("quit",))
    for p in procs:
        p.join(timeout=30)

    print(json.dumps({
        "replicas": args.replicas,
        "requests": args.requests,
        "trace_prefix_lens": meta["prefix_lens"],
        "placements": [placed.count((i, "affine")) for i
                       in range(args.replicas)],
        "spills": router.stats()["spills"],
        "shed": shed,
        "served_sequences": totals["sequences"],
        "served_tokens": totals["tokens"],
    }, indent=2))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Train GPT-2 from scratch with a DeepSpeed-style JSON config.

The minimal end-to-end recipe (the DeepSpeedExamples analogue): config ->
initialize -> train_batch -> save_checkpoint. Runs on one TPU chip as-is;
on a pod, launch with  bin/deepspeed_tpu --hostfile ...  and raise the
mesh axes in the config.

  python examples/train_gpt2.py --steps 20
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import deepspeed_tpu

DS_CONFIG = {
    "train_micro_batch_size_per_gpu": 8,
    "gradient_accumulation_steps": 1,
    "bf16": {"enabled": True},
    "gradient_clipping": 1.0,
    "optimizer": {
        "type": "FusedAdam",
        "params": {"lr": 6e-4, "betas": [0.9, 0.95], "weight_decay": 0.1},
    },
    "scheduler": {
        "type": "WarmupDecayLR",
        "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 6e-4,
                   "warmup_num_steps": 100, "total_num_steps": 10000},
    },
    "zero_optimization": {"stage": 1},
    "steps_per_print": 10,
}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2-125m")
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--save", default=None, help="checkpoint dir")
    args = p.parse_args()

    import jax.numpy as jnp

    from deepspeed_tpu.models.transformer_lm import GPT, gpt2_config

    cfg = gpt2_config(args.model, n_positions=args.seq, dtype=jnp.bfloat16,
                      scan_layers=True, remat=True, remat_policy="selective",
                      use_flash_attention="auto")
    engine, _, _, scheduler = deepspeed_tpu.initialize(
        model=GPT(cfg), config=DS_CONFIG)

    # synthetic corpus stand-in: plug your tokenized dataset in here
    # (or pass training_data= to initialize for the built-in dataloader)
    gb = engine.train_batch_size
    rng = np.random.RandomState(0)

    def batches():
        while True:
            ids = rng.randint(0, cfg.vocab_size,
                              size=(gb, args.seq)).astype(np.int32)
            yield {"input_ids": ids, "labels": ids}

    it = batches()
    for step in range(args.steps):
        loss = engine.train_batch(it)
        if step % 5 == 0:
            print(f"step {step}  loss {float(loss):.4f}  "
                  f"lr {engine.get_lr()[0]:.2e}")
    if args.save:
        engine.save_checkpoint(args.save, tag="example")
        print("checkpoint saved:", args.save)
    print(json.dumps({"final_loss": float(loss), "steps": args.steps}))


if __name__ == "__main__":
    main()

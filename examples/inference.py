#!/usr/bin/env python
"""Serve a model with init_inference: KV-cache scan decode, ragged batches.

  python examples/inference.py --tokens 32
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import deepspeed_tpu


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2-125m")
    p.add_argument("--tokens", type=int, default=32)
    args = p.parse_args()

    import jax.numpy as jnp

    from deepspeed_tpu.models.transformer_lm import GPT, gpt2_config

    cfg = gpt2_config(args.model, dtype=jnp.bfloat16,
                      n_positions=128 + args.tokens)
    engine = deepspeed_tpu.init_inference(
        GPT(cfg), dtype="bf16", replace_with_kernel_inject=True)

    rng = np.random.RandomState(0)
    # a RAGGED batch: three prompts of different lengths, mask marks
    # the real tokens (1) vs pad (0) — generate left-aligns internally
    lens = [128, 64, 96]
    ids = np.zeros((3, 128), np.int32)
    mask = np.zeros((3, 128), np.int32)
    for b, ln in enumerate(lens):
        ids[b, :ln] = rng.randint(0, cfg.vocab_size, ln)
        mask[b, :ln] = 1

    out = engine.generate(ids, attention_mask=mask,
                          max_new_tokens=args.tokens, temperature=0.0)
    print("generated token ids, one row per prompt:")
    print(np.asarray(out))


if __name__ == "__main__":
    main()

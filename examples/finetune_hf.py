#!/usr/bin/env python
"""Fine-tune a HuggingFace torch checkpoint under ZeRO.

`import_hf_model` converts the torch weights into the flax model zoo
(GPT-2/BERT/GPT-J/NeoX/OPT/LLaMA/Mistral/Mixtral/BLOOM/CLIP); the engine
materializes them pre-sharded on the mesh — no zero.Init context needed.

  python examples/finetune_hf.py            # random-weight GPT2 (no net)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import deepspeed_tpu
from deepspeed_tpu.module_inject import import_hf_model

DS_CONFIG = {
    "train_micro_batch_size_per_gpu": 4,
    "bf16": {"enabled": True},
    "gradient_clipping": 1.0,
    "optimizer": {"type": "AdamW", "params": {"lr": 2e-5}},
    "zero_optimization": {"stage": 2},
    "steps_per_print": 5,
}


def main():
    # stand-in for AutoModelForCausalLM.from_pretrained("gpt2") — this
    # environment has no network, so build the architecture with random
    # weights; the conversion path is identical either way
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_model = GPT2LMHeadModel(GPT2Config(n_layer=4, n_embd=256, n_head=8,
                                          n_positions=256))
    model, params = import_hf_model(hf_model)

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=DS_CONFIG, model_parameters=params)

    gb = engine.train_batch_size
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50257, size=(gb, 128)).astype(np.int32)
    it = iter(lambda: {"input_ids": ids, "labels": ids}, None)
    for step in range(10):
        loss = engine.train_batch(it)
    print("fine-tune loss after 10 steps:", float(loss))
    engine.save_16bit_model("/tmp/ds_tpu_example_ft")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Advanced serving compositions in one script.

  python examples/serve_advanced.py --mode int8_tp     # int8 x tensor parallel
  python examples/serve_advanced.py --mode moe_ep      # expert-parallel MoE
  python examples/serve_advanced.py --mode streaming   # past-n_positions decode
  python examples/serve_advanced.py --mode continuous  # continuous batching

int8_tp:    weight-only int8 with the {q, scale} leaves sharded over tp
            (reference init_inference(mp_size=N, dtype=int8)).
moe_ep:     init_inference(ep_size=N) shards the expert stacks over an ep
            mesh axis — an 8-expert model at ep=4 holds 2 experts' weights
            per chip (reference DeepSpeedMoEInference EP groups).
streaming:  a window(+global)-trained rotary model decodes from the ring
            KV cache and generates PAST n_positions at O(window) memory
            (old window blocks evict; leading globals persist — the
            attention-sink pattern).
continuous: the continuous-batching scheduler serves ragged requests
            through a fixed pool of decode slots — a finished sequence's
            lane is refilled by chunked-prefilling the next prompt while
            the other lanes keep decoding; tokens stream per request as
            they land (docs/performance.md "Serving").

On one chip the tp/ep modes run with world size 1 (the sharding is a
no-op); on a mesh they shard as annotated — the same script serves both.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mode", default="streaming",
                   choices=["int8_tp", "moe_ep", "streaming", "continuous"])
    p.add_argument("--tokens", type=int, default=48)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.transformer_lm import GPT, GPTConfig

    n = len(jax.devices())
    rng = np.random.RandomState(0)

    if args.mode == "int8_tp":
        # ~350M params: the size where weight-only int8 starts WINNING
        # (below ~200M decode is dispatch-bound and int8 measures slower;
        # benchmarks/inference/int8_results.json)
        cfg = GPTConfig(vocab_size=50257, n_positions=256, n_embd=1024,
                        n_layer=24, n_head=16, dtype=jnp.bfloat16)
        engine = deepspeed_tpu.init_inference(
            GPT(cfg), mp_size=n, dtype="int8")
        ids = rng.randint(0, cfg.vocab_size, size=(2, 64)).astype(np.int32)
        out = engine.generate(ids, max_new_tokens=args.tokens)
    elif args.mode == "moe_ep":
        ep = n if n in (2, 4, 8) else 1
        cfg = GPTConfig(vocab_size=50257, n_positions=256, n_embd=512,
                        n_layer=4, n_head=8, dtype=jnp.bfloat16,
                        moe_num_experts=8, moe_top_k=2,
                        moe_eval_capacity_factor=2.0)
        engine = deepspeed_tpu.init_inference(
            GPT(cfg), ep_size=ep, dtype="bf16")
        ids = rng.randint(0, cfg.vocab_size,
                          size=(max(ep, 2), 64)).astype(np.int32)
        out = engine.generate(ids, max_new_tokens=args.tokens)
    elif args.mode == "continuous":
        from deepspeed_tpu.inference import ContinuousBatchingScheduler
        from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils \
            import apply_sparse_attention

        cfg = GPTConfig(vocab_size=50257, n_positions=512, n_embd=256,
                        n_layer=4, n_head=8, dtype=jnp.bfloat16,
                        rotary=True, learned_positions=False)
        model = apply_sparse_attention(
            GPT(cfg), {"mode": "local_sliding_window", "block": 32,
                       "num_sliding_window_blocks": 3})  # ring = 64 slots
        engine = deepspeed_tpu.init_inference(model, dtype="bf16")
        sched = ContinuousBatchingScheduler(engine, slots=4)

        def stream(rid, token, done):
            print(f"  req {rid}: token {token}{'  <done>' if done else ''}")

        # ragged prompts, two of them LONGER than the 64-slot ring: those
        # admissions prefill in exact block-aligned chunks
        for n_prompt in (24, 80, 40, 150, 64, 96, 30, 55):
            sched.submit(list(rng.randint(1, cfg.vocab_size, size=n_prompt)),
                         max_new_tokens=min(args.tokens, 12),
                         stream_callback=stream)
        stats = sched.run()
        s = stats.summary()
        print(f"mode=continuous: {s['num_sequences']} sequences, "
              f"{s['total_generated_tokens']} tokens in "
              f"{s['wall_s']:.2f}s ({s['aggregate_tokens_per_s']:.1f} tok/s, "
              f"{s['decode_steps']} batched decode steps) on {n} device(s)")
        return
    else:  # streaming
        from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils \
            import apply_sparse_attention

        cfg = GPTConfig(vocab_size=50257, n_positions=256, n_embd=768,
                        n_layer=12, n_head=12, dtype=jnp.bfloat16,
                        rotary=True, learned_positions=False)
        # ring (1+1)*64 + 64 globals = 192 slots < n_positions=256, so the
        # ring engages and the cap lifts
        model = apply_sparse_attention(
            GPT(cfg), {"mode": "bslongformer", "block": 64,
                       "num_sliding_window_blocks": 3,
                       "attention": "unidirectional"})
        engine = deepspeed_tpu.init_inference(model, dtype="bf16")
        ids = rng.randint(0, cfg.vocab_size, size=(1, 128)).astype(np.int32)
        # 128 + max(384, --tokens) positions through an n_positions=256
        # model: the ring evicts, generation keeps going past the cap
        out = engine.generate(ids, max_new_tokens=max(384, args.tokens),
                              temperature=0.8)

    print(f"mode={args.mode}: generated {np.asarray(out).shape[1]} tokens "
          f"per prompt on {n} device(s)")
    print(np.asarray(out)[:, :16], "...")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Benchmarks: the two BASELINE headline workloads on one TPU chip.

Prints one JSON line per workload,
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
with the north-star metric LAST:

1. BERT-Large MLM pretrain, seq 128 — the reference's headline
   single-device number is 64 TFLOPS / 272 samples-per-sec on one V100
   (BASELINE.md, reference docs/_posts/2020-05-28-fastest-bert-training.md:36).
   Harness: benchmarks/bert_pretrain.py.
2. GPT-2 1.3B pretrain (BASELINE "Target configs" #3, the north star) —
   pure-bf16, largest single-chip training config; vs_baseline is the
   reference's single-device model-at-the-memory-limit number (ZeRO-Offload
   >30 TFLOPS on one V100, docs/_pages/training.md:293).
   Harness: benchmarks/gpt_pretrain.py.

Other harnesses: benchmarks/train_sweep.py, benchmarks/long_context.py,
benchmarks/inference/gpt_bench.py, benchmarks/communication/run_all.py.
"""

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 1)[0])

from benchmarks import bert_pretrain, gpt_pretrain  # noqa: E402

# peak dense bf16 on one v5e chip (public spec, 197 TFLOPS) — the honest
# denominator: MFU = achieved model TFLOPS / peak. The A100 fleet the
# reference reports against runs ~157/312 = 50% MFU at the same scale, so
# matching MFU is the apples-to-apples "matches the reference" claim;
# vs_baseline keeps the reference's own published number as denominator
# and vs_baseline_metric names exactly which number that is.
PEAK_BF16_TFLOPS = 197.0


def main():
    r = bert_pretrain.run("bert-large", seq=128, micro=64, remat=True,
                          remat_policy="selective", steps=10)
    print(json.dumps({
        "metric": "bert_large_seq128_train_tflops_per_chip",
        "value": r["model_tflops"],
        "unit": "TFLOPS",
        "mfu": round(r["model_tflops"] / PEAK_BF16_TFLOPS, 3),
        "vs_baseline": round(
            r["model_tflops"] / bert_pretrain.BASELINE_TFLOPS, 3),
        "vs_baseline_metric": "reference headline 64 TFLOPS on one V100 "
                              "(docs/_posts/2020-05-28-fastest-bert-"
                              "training.md)",
        "samples_per_sec": r["samples_per_sec"],
        "samples_per_sec_vs_baseline": round(
            r["samples_per_sec"] / bert_pretrain.BASELINE_SAMPLES_SEC, 3),
        "ms_per_step": r["ms_per_step"],
        "seq_len": r["seq"],
        "global_batch": r["global_batch"],
        "n_devices": r["n_devices"],
    }), flush=True)

    # free the BERT engine's device buffers (engine<->adapter cycle needs a
    # GC pass) before the 1.3B model takes nearly all of HBM
    import gc

    gc.collect()

    g = gpt_pretrain.run()
    mfu = g["model_tflops"] / PEAK_BF16_TFLOPS
    print(json.dumps({
        "metric": "gpt2_1.3b_seq1024_train_tflops_per_chip",
        "value": g["model_tflops"],
        "unit": "TFLOPS",
        "mfu": round(mfu, 3),
        "mfu_reference_a100_fleet": 0.50,  # 157/312 published A100 MFU
        # the honest headline ratio: matched-scale MFU vs the reference's
        # published A100-fleet utilization. The only single-DEVICE 1.3B
        # number the reference publishes is a ZeRO-Offload config (30
        # TFLOPS, docs/_pages/training.md:293) — beating an offload config
        # from HBM is not a like-for-like win, so that ratio is reported
        # under its own name below, not as vs_baseline.
        "vs_baseline": round(mfu / 0.50, 3),
        "vs_baseline_metric": "MFU vs the reference A100 fleet's ~50% MFU "
                              "at the same scale (157/312 published)",
        "vs_v100_zero_offload_30tflops": round(
            g["model_tflops"] / gpt_pretrain.BASELINE_TFLOPS, 3),
        "samples_per_sec": g["samples_per_sec"],
        "ms_per_step": g["ms_per_step"],
        "seq_len": g["seq"],
        "global_batch": g["global_batch"],
        "n_devices": g["n_devices"],
    }), flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Benchmarks: the two BASELINE headline workloads on one TPU chip.

Prints one JSON line per workload,
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
with the north-star metric LAST:

1. BERT-Large MLM pretrain, seq 128 — the reference's headline
   single-device number is 64 TFLOPS / 272 samples-per-sec on one V100
   (BASELINE.md, reference docs/_posts/2020-05-28-fastest-bert-training.md:36).
   Harness: benchmarks/bert_pretrain.py.
2. GPT-2 1.3B pretrain (BASELINE "Target configs" #3, the north star) —
   pure-bf16, largest single-chip training config; vs_baseline is the
   reference's single-device model-at-the-memory-limit number (ZeRO-Offload
   >30 TFLOPS on one V100, docs/_pages/training.md:293).
   Harness: benchmarks/gpt_pretrain.py.

Every run emits evidence: the backend is preflighted in a subprocess
(one retry with backoff) before jax is touched in-process, each workload
gets one retry, and a workload that still fails prints a JSON line with
an "error" field instead of dying silently — a backend hiccup never
yields an evidence-free rc=1 (ROADMAP item 1).

Other harnesses: benchmarks/train_sweep.py, benchmarks/long_context.py,
benchmarks/inference/gpt_bench.py, benchmarks/communication/run_all.py.
"""

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 1)[0])

from benchmarks._util import backend_preflight, run_with_retry  # noqa: E402

# Peak dense bf16 per chip. The table in profiling/step_profiler.py keys
# on the detected device kind (v5e -> 197, the public spec) — the honest
# MFU denominator. The A100 fleet the reference reports against runs
# ~157/312 = 50% MFU at the same scale, so matching MFU is the
# apples-to-apples "matches the reference" claim; vs_baseline keeps the
# reference's own published number as denominator and vs_baseline_metric
# names exactly which number that is.
_FALLBACK_PEAK_TFLOPS = 197.0  # v5e public spec


def _emit(obj):
    print(json.dumps(obj), flush=True)


def _peak_tflops() -> float:
    try:
        from deepspeed_tpu.profiling.step_profiler import peak_tflops

        return peak_tflops()[0]
    except Exception:
        return _FALLBACK_PEAK_TFLOPS


def _analytic_fields(r: dict) -> dict:
    """Pass through the compiled-step cost-analysis numbers when the
    harness produced them (benchmarks/_util.analytic_step_metrics)."""
    keys = ("analytic_tflops", "analytic_mfu", "analytic_flops_per_step",
            "hbm_gb_per_s")
    return {k: r[k] for k in keys if k in r}


def main() -> int:
    pre = backend_preflight(max_tries=2, backoff_s=10.0, emit=_emit)
    if not pre["ok"]:
        _emit({"metric": "bench_aborted", "error": pre["error"],
               "preflight_attempts": pre["attempts"]})
        return 1
    _emit({"event": "backend_preflight_ok", "backend": pre["backend"],
           "attempts": pre["attempts"]})

    from benchmarks import bert_pretrain, gpt_pretrain

    peak = _peak_tflops()
    failures = 0

    r, err = run_with_retry(
        lambda: bert_pretrain.run("bert-large", seq=128, micro=64,
                                  remat=True, remat_policy="selective",
                                  steps=10),
        "bert_large_seq128", retries=1, backoff_s=5.0, emit=_emit)
    if r is not None:
        _emit({
            "metric": "bert_large_seq128_train_tflops_per_chip",
            "value": r["model_tflops"],
            "unit": "TFLOPS",
            "mfu": round(r["model_tflops"] / peak, 3),
            "vs_baseline": round(
                r["model_tflops"] / bert_pretrain.BASELINE_TFLOPS, 3),
            "vs_baseline_metric": "reference headline 64 TFLOPS on one V100 "
                                  "(docs/_posts/2020-05-28-fastest-bert-"
                                  "training.md)",
            "samples_per_sec": r["samples_per_sec"],
            "samples_per_sec_vs_baseline": round(
                r["samples_per_sec"] / bert_pretrain.BASELINE_SAMPLES_SEC, 3),
            "ms_per_step": r["ms_per_step"],
            "seq_len": r["seq"],
            "global_batch": r["global_batch"],
            "n_devices": r["n_devices"],
            **_analytic_fields(r),
        })
    else:
        failures += 1
        _emit({"metric": "bert_large_seq128_train_tflops_per_chip",
               "value": None, "unit": "TFLOPS", "error": err})

    # free the BERT engine's device buffers (engine<->adapter cycle needs a
    # GC pass) before the 1.3B model takes nearly all of HBM
    import gc

    gc.collect()

    g, err = run_with_retry(gpt_pretrain.run, "gpt2_1.3b_seq1024",
                            retries=1, backoff_s=5.0, emit=_emit)
    if g is not None:
        mfu = g["model_tflops"] / peak
        _emit({
            "metric": "gpt2_1.3b_seq1024_train_tflops_per_chip",
            "value": g["model_tflops"],
            "unit": "TFLOPS",
            "mfu": round(mfu, 3),
            "mfu_reference_a100_fleet": 0.50,  # 157/312 published A100 MFU
            # the honest headline ratio: matched-scale MFU vs the reference's
            # published A100-fleet utilization. The only single-DEVICE 1.3B
            # number the reference publishes is a ZeRO-Offload config (30
            # TFLOPS, docs/_pages/training.md:293) — beating an offload config
            # from HBM is not a like-for-like win, so that ratio is reported
            # under its own name below, not as vs_baseline.
            "vs_baseline": round(mfu / 0.50, 3),
            "vs_baseline_metric": "MFU vs the reference A100 fleet's ~50% "
                                  "MFU at the same scale (157/312 published)",
            "vs_v100_zero_offload_30tflops": round(
                g["model_tflops"] / gpt_pretrain.BASELINE_TFLOPS, 3),
            "samples_per_sec": g["samples_per_sec"],
            "ms_per_step": g["ms_per_step"],
            "seq_len": g["seq"],
            "global_batch": g["global_batch"],
            "n_devices": g["n_devices"],
            **_analytic_fields(g),
        })
    else:
        failures += 1
        _emit({"metric": "gpt2_1.3b_seq1024_train_tflops_per_chip",
               "value": None, "unit": "TFLOPS", "error": err})

    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())

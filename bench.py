#!/usr/bin/env python
"""Benchmark: BERT-Large MLM pretraining throughput on one TPU chip.

The reference's headline single-device number is 64 TFLOPS / 272
samples-per-sec for BERT-Large at seq 128 on one V100 (BASELINE.md,
reference docs/_posts/2020-05-28-fastest-bert-training.md:36) — this is
the SAME workload measured the same way (see benchmarks/bert_pretrain.py,
which owns the harness). Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

GPT-2 family training benches: benchmarks/train_sweep.py (350M reaches
~70 TFLOPS), long-context: benchmarks/long_context.py, inference latency:
benchmarks/inference/gpt_bench.py.
"""

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 1)[0])

from benchmarks.bert_pretrain import (  # noqa: E402
    BASELINE_SAMPLES_SEC,
    BASELINE_TFLOPS,
    run,
)


def main():
    r = run("bert-large", seq=128, micro=64, remat=True,
            remat_policy="selective", steps=10)
    result = {
        "metric": "bert_large_seq128_train_tflops_per_chip",
        "value": r["model_tflops"],
        "unit": "TFLOPS",
        "vs_baseline": round(r["model_tflops"] / BASELINE_TFLOPS, 3),
        "samples_per_sec": r["samples_per_sec"],
        "samples_per_sec_vs_baseline": round(
            r["samples_per_sec"] / BASELINE_SAMPLES_SEC, 3),
        "ms_per_step": r["ms_per_step"],
        "seq_len": r["seq"],
        "global_batch": r["global_batch"],
        "n_devices": r["n_devices"],
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Benchmark: GPT-2 350M bf16 training throughput on one TPU chip.

Mirrors the BASELINE GPT-2 training family (configs 2-3) on the available
hardware: 350M is the largest GPT-2 size whose fp32 optimizer states fit
this chip's HBM without offload, and sits between config 2 (125M) and the
1.3B north star. 125M and other sizes: benchmarks/train_sweep.py. Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline normalizes achieved model TFLOPS against the reference's best
published single-device number: 64 TFLOPS on 1x V100 for BERT-L seq-128
pretraining (reference docs/_posts/2020-05-28-fastest-bert-training.md:36,
see BASELINE.md).
"""

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])

BASELINE_TFLOPS = 64.0


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.transformer_lm import (
        GPT,
        gpt2_config,
        num_params,
    )

    seq = 1024
    micro = 8
    cfg = gpt2_config(
        "gpt2-350m",
        n_positions=seq,
        dtype=jnp.bfloat16,
        scan_layers=True,
        remat=True,
        remat_policy="selective",   # save MXU outputs, recompute VPU work
        use_flash_attention=True,   # Pallas blockwise attention
    )
    model = GPT(cfg)
    ds_config = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "optimizer": {
            "type": "FusedAdam",
            "params": {"lr": 6e-4, "betas": [0.9, 0.95], "weight_decay": 0.1},
        },
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds_config)

    n_dev = engine.topology.num_devices
    gb = micro * engine.topology.data_parallel_size
    rng = np.random.RandomState(0)
    batch = {
        "input_ids": rng.randint(0, cfg.vocab_size, size=(gb, seq)).astype(np.int32)
    }
    batch["labels"] = batch["input_ids"]

    from deepspeed_tpu.runtime.dataloader import RepeatingLoader

    it = iter(RepeatingLoader([batch]))

    def one_step():
        engine.train_batch(it)  # fused single-program step when gas == 1

    def fence():
        # scalar-only host read: on tunneled backends block_until_ready can
        # return before the compute queue drains, and converting a full
        # array pulls megabytes over the wire — a device-side reduction
        # read back as one float is the only honest fence
        return float(jnp.sum(jax.tree.leaves(engine.params)[0]
                             .astype(jnp.float32)))

    # compile + warmup
    one_step()
    one_step()
    fence()

    steps = 10
    t0 = time.time()
    for _ in range(steps):
        one_step()
    fence()
    dt = (time.time() - t0) / steps

    tokens_per_step = gb * seq
    n_params = num_params(cfg)
    embed = cfg.vocab_size * cfg.n_embd
    # model flops/token: 6*(N - embed) matmul + causal attention
    attn = 6 * cfg.n_layer * cfg.n_embd * seq  # 12*L*C*s/2 (causal)
    flops_per_token = 6.0 * (n_params - embed) + attn
    tflops = tokens_per_step * flops_per_token / dt / 1e12 / n_dev
    samples_per_sec = gb / dt

    result = {
        "metric": "gpt2_350m_bf16_train_tflops_per_chip",
        "value": round(tflops, 2),
        "unit": "TFLOPS",
        "vs_baseline": round(tflops / BASELINE_TFLOPS, 3),
        "samples_per_sec": round(samples_per_sec, 2),
        "ms_per_step": round(dt * 1000, 1),
        "seq_len": seq,
        "global_batch": gb,
        "n_devices": n_dev,
        "params_m": round(n_params / 1e6, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()

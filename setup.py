"""Install hooks for deepspeed_tpu (metadata lives in pyproject.toml).

Ahead-of-time native-op build, the analogue of the reference's
``DS_BUILD_*`` flags (reference setup.py:115-163): by default the C++ host
ops (CPU Adam/Adagrad, aio threadpool) JIT-compile on first use via
``ops/native/builder.py``; with

    DS_BUILD_OPS=1 pip install .

they are compiled at install time into ``deepspeed_tpu/ops/native/prebuilt/``
and the builder loads them without ever invoking a compiler on the target
machine. The AOT library is built WITHOUT ``-march=native`` (it must run on
any x86-64 target, not just the build host) and is content-hashed against
the shipped sources, so a stale prebuilt is ignored, never mis-loaded.

The builder module is loaded standalone from its file path — importing the
``deepspeed_tpu`` package would pull in jax, which is absent from pip's
isolated PEP 517 build environment.
"""

import importlib.util
import os

from setuptools import setup
from setuptools.command.build_py import build_py


def _load_builder(path):
    spec = importlib.util.spec_from_file_location("_ds_native_builder", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class build_py_with_ops(build_py):
    def run(self):
        super().run()
        if os.environ.get("DS_BUILD_OPS") != "1":
            return
        pkg = os.path.join(self.build_lib, "deepspeed_tpu", "ops", "native")
        try:
            builder = _load_builder(os.path.join(pkg, "builder.py"))
            dest = os.path.join(pkg, "prebuilt")
            os.makedirs(dest, exist_ok=True)
            name = f"libds_tpu_native_{builder._content_hash()}.so"
            builder.build(verbose=True, portable=True,
                          out_path=os.path.join(dest, name))
        except RuntimeError as e:
            raise SystemExit(
                f"DS_BUILD_OPS=1 but the native op build failed: {e}\n"
                "Unset DS_BUILD_OPS to fall back to JIT-on-first-use."
            )


setup(cmdclass={"build_py": build_py_with_ops})
